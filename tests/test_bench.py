"""`dsst bench` — the performance-observability tier.

Four layers under test, cheapest first:

- **stats core**: synthetic timing distributions through warmup
  discard, median/MAD, dispersion-derived tolerance, and the
  regression/improvement/within-noise verdict vocabulary — no workload.
- **baseline**: fingerprint-keyed add/expire/reopen round-trips, the
  reason-mandatory contract, foreign-fingerprint isolation.
- **the registry + runner**: framework-owned repetition loop with
  durable partials, child JSON protocol, registry coverage, and the
  synthetic-regression exit-1 acceptance gate through the real CLI.
- **integrations**: the feeder_e2e attribution cross-check (self-
  verifying harness), achieved-FLOPs/s gauges priced by the audit
  baseline, and the profile merge (flight-recorder spans + jax.profiler
  events in ONE Perfetto file).
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

from dss_ml_at_scale_tpu import telemetry
from dss_ml_at_scale_tpu.bench import (
    BenchUsageError,
    Metric,
    Scenario,
    environment_fingerprint,
    fingerprint_key,
    get_scenario,
    load_bench_baseline,
    measure_scenario,
    run_bench,
    scenario_names,
    write_bench_baseline,
)
from dss_ml_at_scale_tpu.bench import core as bench_core
from dss_ml_at_scale_tpu.bench import stats
from dss_ml_at_scale_tpu.config.cli import main

REPO = Path(__file__).resolve().parents[1]


# -- stats core ---------------------------------------------------------------


def test_warmup_discard():
    assert stats.discard_warmup([9.0, 1.0, 1.1, 1.2], 1) == [1.0, 1.1, 1.2]
    assert stats.discard_warmup([1.0], 0) == [1.0]
    with pytest.raises(ValueError):
        stats.discard_warmup([1.0], -1)


def test_median_and_mad_robust_to_outlier():
    # One stalled repetition must not move the summary the way it moves
    # a mean/stddev: that is the whole reason the harness uses
    # median/MAD.
    clean = [100.0, 101.0, 99.0, 100.5, 99.5]
    stalled = clean + [400.0]
    s_clean = stats.summarize(clean)
    s_stalled = stats.summarize(stalled)
    assert abs(s_clean.median - 100.0) <= 0.5
    assert abs(s_stalled.median - s_clean.median) <= 1.0
    assert s_stalled.mad < 5.0
    assert stats.median([1.0, 3.0]) == 2.0  # even-length interpolation


def test_tolerance_derives_from_dispersion():
    quiet = stats.Summary(median=100.0, mad=0.5, n=5)
    noisy = stats.Summary(median=100.0, mad=20.0, n=5)
    # Quiet on both sides: the floor rules.
    assert stats.tolerance(quiet, quiet, floor=0.25) == 0.25
    # A noisy side widens the band beyond the floor (4 * 20/100 = 0.8).
    assert stats.tolerance(quiet, noisy, floor=0.25) == pytest.approx(0.8)
    assert stats.tolerance(noisy, quiet, floor=0.25) == pytest.approx(0.8)


def test_large_regression_cannot_inflate_its_own_tolerance():
    """Each side's MAD normalizes by its OWN median: a 10x lower-is-
    better regression whose absolute noise scaled with the regressed
    value (MAD 100 on median 1000 = 10% relative) must not widen the
    band past the change it is being judged for."""
    base = stats.Summary(median=100.0, mad=1.0, n=5)
    regressed = stats.Summary(median=1000.0, mad=100.0, n=5)
    tol = stats.tolerance(regressed, base, floor=0.25)
    assert tol == pytest.approx(0.4)  # 4 * (100/1000), NOT 4 * (100/100)
    out = stats.classify("lower", regressed, base, floor=0.25)
    assert out["verdict"] == "regression"


@pytest.mark.parametrize("direction,cur,verdict", [
    ("higher", 30.0, "regression"),
    ("higher", 170.0, "improvement"),
    ("higher", 95.0, "within-noise"),
    ("lower", 170.0, "regression"),
    ("lower", 30.0, "improvement"),
    ("lower", 105.0, "within-noise"),
])
def test_classify_verdicts(direction, cur, verdict):
    base = stats.Summary(median=100.0, mad=1.0, n=5)
    out = stats.classify(
        direction, stats.Summary(median=cur, mad=1.0, n=5), base,
        floor=0.35,
    )
    assert out["verdict"] == verdict
    assert out["tolerance"] == pytest.approx(0.35)


def test_classify_edges():
    cur = stats.Summary(median=50.0, mad=1.0, n=5)
    assert stats.classify("higher", cur, None)["verdict"] == "no-baseline"
    zero = stats.Summary(median=0.0, mad=0.0, n=5)
    assert stats.classify("higher", cur, zero)["verdict"] == "no-baseline"
    base = stats.Summary(median=100.0, mad=0.0, n=5)
    assert stats.classify(
        "higher", cur, base, gate=False
    )["verdict"] == "informational"
    with pytest.raises(ValueError):
        stats.classify("sideways", cur, base)


# -- synthetic scenarios (framework loop, baseline round-trips) ---------------


def _synth_scenario(values, name="synth", warmup=1, extra=None):
    it = iter(values)

    def measure(_ctx):
        out = {"synth_metric": next(it)}
        if extra is not None:
            out["_extra"] = extra
        return out

    return Scenario(
        name=name,
        description="synthetic",
        tier="tier1",
        metrics=(Metric("synth_metric", "units", "higher", floor=0.25),),
        measure=measure,
        repetitions=len(values) - warmup,
        warmup=warmup,
    )


@pytest.fixture
def synth_registry(monkeypatch):
    """Injects synthetic scenarios into the live registry (restored
    after the test) and returns a register(sc) helper."""
    bench_core._load_scenarios()

    def register(sc):
        monkeypatch.setitem(bench_core._SCENARIOS, sc.name, sc)
        return sc

    return register


def test_measure_scenario_discards_warmup_and_checkpoints(tmp_path):
    sc = _synth_scenario([999.0, 10.0, 11.0, 12.0], warmup=1)
    partial = tmp_path / "partial.json"
    record = measure_scenario(sc, partial_path=partial, env={})
    assert record["samples"]["synth_metric"] == [10.0, 11.0, 12.0]
    assert record["completed"] == 3
    # The durable partial holds the same post-warmup record (salvage
    # input for a watchdog-killed child).
    assert json.loads(partial.read_text()) == record


def test_measure_scenario_rejects_undeclared_metric():
    sc = Scenario(
        name="synth", description="", tier="tier1",
        metrics=(Metric("declared", "u"),),
        measure=lambda ctx: {"undeclared": 1.0},
        repetitions=1, warmup=0,
    )
    with pytest.raises(BenchUsageError, match="undeclared"):
        measure_scenario(sc, env={})


def test_scenario_schema_validation():
    with pytest.raises(ValueError, match="direction"):
        Metric("m", "u", "sideways")
    with pytest.raises(ValueError, match="tier"):
        Scenario(name="x", description="", tier="warp",
                 metrics=(), measure=lambda c: {})
    with pytest.raises(ValueError, match="steps_metric"):
        Scenario(name="x", description="", tier="tier1",
                 metrics=(Metric("m", "u"),), measure=lambda c: {},
                 steps_metric="absent")


def test_run_bench_judges_against_fingerprinted_baseline(
    tmp_path, synth_registry,
):
    register = synth_registry
    env = environment_fingerprint()
    fp = fingerprint_key(env)
    bl = tmp_path / "BENCH_BASELINE.json"

    # Round 1: no baseline -> no-baseline verdict, exit 0.
    register(_synth_scenario([100.0, 100.0, 101.0, 99.0]))
    res = run_bench(["synth"], baseline_path=bl, isolation=False)
    m = res.results["synth"]["metrics"]["synth_metric"]
    assert m["verdict"] == "no-baseline"
    assert res.exit_code == 0

    # Record it (new entry needs --reason).
    with pytest.raises(BenchUsageError, match="reason"):
        write_bench_baseline(bl, res, load_bench_baseline(bl), None)
    write_bench_baseline(bl, res, load_bench_baseline(bl), "initial")
    data = json.loads(bl.read_text())
    entry = data["entries"][fp]["scenarios"]["synth"]
    assert entry["reason"] == "initial"
    assert entry["metrics"]["synth_metric"]["median"] == 100.0

    # Round 2: same numbers -> within-noise, exit 0.
    register(_synth_scenario([100.0, 100.0, 101.0, 99.0]))
    res = run_bench(["synth"], baseline_path=bl, isolation=False)
    assert res.results["synth"]["metrics"]["synth_metric"]["verdict"] \
        == "within-noise"
    assert res.exit_code == 0

    # Round 3: collapse -> regression, exit 1 (the acceptance contract).
    register(_synth_scenario([50.0, 50.0, 51.0, 49.0]))
    res = run_bench(["synth"], baseline_path=bl, isolation=False)
    assert res.results["synth"]["metrics"]["synth_metric"]["verdict"] \
        == "regression"
    assert res.exit_code == 1
    assert any(f["kind"] == "regression" for f in res.findings)

    # Round 4: a re-baseline keeps the authored reason and reopens the
    # gate at the new level.
    write_bench_baseline(bl, res, load_bench_baseline(bl), None)
    data = json.loads(bl.read_text())
    entry = data["entries"][fp]["scenarios"]["synth"]
    assert entry["reason"] == "initial"  # kept, not re-required
    assert entry["metrics"]["synth_metric"]["median"] == 50.0


def test_foreign_fingerprint_entries_never_gate_or_expire(
    tmp_path, synth_registry,
):
    register = synth_registry
    bl = tmp_path / "BENCH_BASELINE.json"
    foreign = {
        "env": {"platform": "tpu"},
        "scenarios": {
            "long_gone_scenario": {"reason": "tpu box truth",
                                   "metrics": {"x": {"median": 1.0}}},
        },
    }
    bl.write_text(json.dumps({
        "version": 1, "entries": {"tpu:v5:8dev:jax9:py3:64cpu": foreign},
    }))
    register(_synth_scenario([5.0, 5.0], warmup=1))
    res = run_bench(["synth"], baseline_path=bl, isolation=False)
    # The foreign entry names an unregistered scenario — but it belongs
    # to another environment, so it neither gates nor goes stale here.
    assert res.exit_code == 0
    write_bench_baseline(bl, res, load_bench_baseline(bl), "r")
    data = json.loads(bl.read_text())
    assert data["entries"]["tpu:v5:8dev:jax9:py3:64cpu"] == foreign


def test_stale_baseline_entries_fail(tmp_path, synth_registry):
    register = synth_registry
    env = environment_fingerprint()
    fp = fingerprint_key(env)
    bl = tmp_path / "BENCH_BASELINE.json"
    bl.write_text(json.dumps({
        "version": 1,
        "entries": {fp: {"env": env, "scenarios": {
            "unregistered_scenario": {
                "reason": "r", "metrics": {"x": {"median": 1.0}}},
            "synth": {"reason": "r", "metrics": {
                "synth_metric": {"median": 5.0, "mad": 0.0, "n": 3},
                "dropped_metric": {"median": 2.0, "mad": 0.0, "n": 3},
            }},
        }}},
    }))
    register(_synth_scenario([5.0, 5.0], warmup=1))
    res = run_bench(["synth"], baseline_path=bl, isolation=False)
    stale = [f for f in res.findings if f["kind"] == "stale"]
    assert res.exit_code == 1
    assert {f.get("scenario") for f in stale} == {
        "unregistered_scenario", "synth",
    }
    # --update-baseline sheds both kinds of ballast.
    write_bench_baseline(bl, res, load_bench_baseline(bl), "r")
    data = json.loads(bl.read_text())
    scen = data["entries"][fp]["scenarios"]
    assert "unregistered_scenario" not in scen
    assert "dropped_metric" not in scen["synth"]["metrics"]
    register(_synth_scenario([5.0, 5.0], warmup=1))
    assert run_bench(["synth"], baseline_path=bl,
                     isolation=False).exit_code == 0


def test_extra_block_carried_into_report(synth_registry, tmp_path):
    register = synth_registry
    register(_synth_scenario([1.0, 1.0], warmup=1,
                             extra={"detail": {"k": "v"}}))
    res = run_bench(["synth"], baseline_path=tmp_path / "b.json",
                    isolation=False)
    assert res.results["synth"]["extra"] == {"detail": {"k": "v"}}


def test_update_baseline_refuses_salvaged_results(
    tmp_path, synth_registry,
):
    """A record salvaged from a killed child is reportable but must not
    become the committed truth — a median-of-one from a wedged host
    would silently weaken the gate for every future run."""
    register = synth_registry
    register(_synth_scenario([5.0, 5.0], warmup=1))
    res = run_bench(["synth"], baseline_path=tmp_path / "b.json",
                    isolation=False)
    res.results["synth"]["salvaged"] = True
    with pytest.raises(BenchUsageError, match="salvaged"):
        write_bench_baseline(tmp_path / "b.json", res,
                             {"entries": {}}, "r")


def test_profile_repetitions_flag_reaches_the_profile(monkeypatch):
    """`dsst bench --repetitions 5 profile X` and `dsst bench profile X
    --repetitions 3` must both reach profile_scenario (a shared
    argparse dest let the subparser default clobber the parent value)."""
    from dss_ml_at_scale_tpu.bench import profile as profile_mod

    seen = {}

    def fake_profile(name, out, *, repetitions, min_profiler_dur_us):
        seen["reps"] = repetitions
        return {"out": str(out), "spans": 0, "flows": 0,
                "profiler_events": 0, "profiler_events_dropped": 0,
                "mfu": None}

    monkeypatch.setattr(profile_mod, "profile_scenario", fake_profile)
    assert main(["bench", "--repetitions", "5", "profile", "feeder_e2e",
                 "--out", "/tmp/x.json"]) == 0
    assert seen["reps"] == 5
    assert main(["bench", "profile", "feeder_e2e", "--repetitions", "3",
                 "--out", "/tmp/x.json"]) == 0
    assert seen["reps"] == 3
    assert main(["bench", "profile", "feeder_e2e",
                 "--out", "/tmp/x.json"]) == 0
    assert seen["reps"] == 1


def test_require_baseline_fails_ungated_host(tmp_path, synth_registry):
    register = synth_registry
    register(_synth_scenario([5.0, 5.0], warmup=1))
    bl = tmp_path / "empty.json"
    res = run_bench(["synth"], baseline_path=bl, isolation=False)
    assert res.exit_code == 0  # default: no-baseline passes
    register(_synth_scenario([5.0, 5.0], warmup=1))
    res = run_bench(["synth"], baseline_path=bl, isolation=False,
                    require_baseline=True)
    assert res.exit_code == 1
    assert any(f["kind"] == "no-baseline" for f in res.findings)


def test_in_process_scenario_defect_is_finding_not_usage_error(
    tmp_path, synth_registry,
):
    """A broken scenario must judge identically in-process and in child
    isolation: an error finding with exit 1, never a whole-run abort."""
    register = synth_registry
    register(Scenario(
        name="synth", description="", tier="tier1",
        metrics=(Metric("declared", "u"),),
        measure=lambda ctx: {"undeclared": 1.0},
        repetitions=1, warmup=0,
    ))
    res = run_bench(["synth"], baseline_path=tmp_path / "b.json",
                    isolation=False)
    assert res.exit_code == 1
    assert any(f["kind"] == "error" and "undeclared" in f["message"]
               for f in res.findings)
    # Pre-run flag errors stay usage errors in both modes.
    with pytest.raises(BenchUsageError, match="repetitions"):
        run_bench(["synth"], baseline_path=tmp_path / "b.json",
                  isolation=False, repetitions=0)


def test_recorder_scenario_parks_and_restores_live_recorder(tmp_path):
    """recorder_overhead must own the recorder for both halves of its
    comparison and hand back whatever tail was live before (a tracked
    run or `dsst bench profile` must not lose its recorder, nor absorb
    the scenario's synthetic events)."""
    from dss_ml_at_scale_tpu.telemetry import flightrec

    sc = get_scenario("recorder_overhead")
    outer = tmp_path / "outer_tail.jsonl"
    flightrec.enable(outer)
    try:
        ctx = sc.setup()
        try:
            out = sc.measure(ctx)
        finally:
            sc.teardown(ctx)
        assert flightrec.get_recorder().path == outer.absolute()
        assert out["recorder_emit_tail_us"] > 0
        # No synthetic bench event leaked into the parked outer tail.
        assert not any(
            e.get("thread") == "bench"
            for e in flightrec.read_events(outer)
        )
    finally:
        flightrec.disable(outer)


def test_salvage_partial_contract(tmp_path):
    p = tmp_path / "partial.json"
    assert bench_core._salvage_partial(p) is None  # missing
    p.write_text(json.dumps({"scenario": "x", "completed": 0}))
    assert bench_core._salvage_partial(p) is None  # nothing measured
    p.write_text(json.dumps({"scenario": "x", "completed": 2,
                             "samples": {"m": [1, 2]}}))
    assert bench_core._salvage_partial(p)["completed"] == 2


# -- registry + catalog reconciliation (runtime side of the lint) -------------


def test_registry_matches_catalog_and_spans():
    from dss_ml_at_scale_tpu.telemetry.catalog import (
        KNOWN_BENCH_METRICS,
        KNOWN_SPANS,
        SPAN_ATTRIBUTION,
    )

    names = scenario_names()
    assert set(names) == set(KNOWN_BENCH_METRICS)
    for name in names:
        sc = get_scenario(name)
        assert tuple(m.name for m in sc.metrics) == tuple(
            KNOWN_BENCH_METRICS[name]
        ), name
    # The attribution mapping buckets only declared spans — the
    # single-sourcing fix this PR exists to pin.
    assert set(SPAN_ATTRIBUTION) <= set(KNOWN_SPANS)
    assert set(SPAN_ATTRIBUTION.values()) <= {
        "data_wait", "transfer", "compute", "host",
    }


# -- child protocol -----------------------------------------------------------


def _run_child(args, timeout=240):
    return subprocess.run(
        [sys.executable, "-m", "dss_ml_at_scale_tpu.bench", *args],
        capture_output=True, text=True, timeout=timeout, cwd=str(REPO),
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )


def test_child_protocol_success_and_partial(tmp_path):
    partial = tmp_path / "p.json"
    proc = _run_child([
        "--scenario", "sanitizer_overhead", "--partial", str(partial),
        "--repetitions", "2",
    ])
    assert proc.returncode == 0, proc.stderr[-2000:]
    record = json.loads(proc.stdout.strip().splitlines()[-1])
    assert record["scenario"] == "sanitizer_overhead"
    assert record["completed"] == 2
    assert len(record["samples"]["sanitizer_overhead_ratio"]) == 2
    # The durable partial mirrors the final record — what a watchdog
    # kill would salvage.
    assert json.loads(partial.read_text())["completed"] == 2


def test_child_protocol_failure_is_json_not_crash():
    proc = _run_child(["--scenario", "no_such_scenario"], timeout=120)
    assert proc.returncode == 0
    record = json.loads(proc.stdout.strip().splitlines()[-1])
    assert record["failed"] is True
    assert "no_such_scenario" in record["error"]


# -- CLI ----------------------------------------------------------------------


def test_cli_usage_errors():
    assert main(["bench", "--scenarios", "decode", "--tier", "tier1"]) == 2
    assert main(["bench", "--scenarios", "no_such"]) == 2
    assert main(["bench", "--tier", "warp"]) == 2


def test_cli_list_scenarios(capsys):
    assert main(["bench", "--list-scenarios"]) == 0
    out = capsys.readouterr().out
    for name in scenario_names():
        assert name in out


def test_cli_synthetic_regression_exits_nonzero(tmp_path, capsys):
    """Acceptance gate: a committed baseline whose numbers this host
    cannot meet must fail `dsst bench` with exit 1 — through the real
    CLI, the real child, and the real verdict path."""
    env = environment_fingerprint()
    fp = fingerprint_key(env)
    bl = tmp_path / "BENCH_BASELINE.json"
    bl.write_text(json.dumps({
        "version": 1,
        "entries": {fp: {"env": env, "scenarios": {
            "sanitizer_overhead": {
                "reason": "synthetic: impossible ratio",
                "metrics": {
                    # lower-is-better with an unreachable baseline: any
                    # real measurement is a regression beyond tolerance.
                    "sanitizer_overhead_ratio": {
                        "median": 0.001, "mad": 0.0, "n": 5},
                },
            },
        }}},
    }))
    rc = main(["bench", "--scenarios", "sanitizer_overhead", "--json",
               "--baseline", str(bl)])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert report["ok"] is False
    assert report["counts"]["regressions"] == 1
    m = report["results"]["sanitizer_overhead"]["metrics"][
        "sanitizer_overhead_ratio"]
    assert m["verdict"] == "regression"


def test_cli_tier1_smoke_gate(capsys):
    """The CI gate: the full tier-1 subset runs in isolated children
    against the committed BENCH_BASELINE.json with registry coverage —
    a scenario silently dropping out of the run is a finding, and the
    exit code is the report's verdict."""
    registered_tier1 = {
        n for n in scenario_names() if get_scenario(n).tier == "tier1"
    }
    rc = main(["bench", "--tier", "tier1", "--json"])
    report = json.loads(capsys.readouterr().out)
    # Coverage: every registered tier-1 scenario both selected AND
    # measured (a child crash surfaces as an error finding + rc 1).
    assert set(report["scenarios"]) == registered_tier1
    assert set(report["results"]) == registered_tier1
    bad = [f for f in report["findings"]
           if f["kind"] in ("error", "timeout", "no-samples", "stale")]
    assert bad == [], bad
    assert rc == (0 if report["ok"] else 1)
    # The committed baseline speaks for this fingerprint: every gated
    # tier-1 metric must have found a baseline to be judged against.
    for name in registered_tier1:
        for mname, m in report["results"][name]["metrics"].items():
            if get_scenario(name).metric(mname).gate:
                assert m["verdict"] != "no-baseline", (name, mname)
    # The achieved-FLOPs/s block priced by the audit pin rode along.
    assert "train_step.classifier" in report["mfu"]
    assert report["mfu"]["train_step.classifier"][
        "achieved_flops_per_sec"] > 0


# -- feeder_e2e cross-check + MFU + profile -----------------------------------


@pytest.fixture(scope="module")
def feeder_ctx():
    sc = get_scenario("feeder_e2e")
    ctx = sc.setup()
    yield sc, ctx
    sc.teardown(ctx)


def test_feeder_e2e_crosscheck_passes(feeder_ctx):
    sc, ctx = feeder_ctx
    out = sc.measure(ctx)
    assert out["e2e_images_per_sec"] > 0
    # The loop is fully span-covered: reader.next/feeder.place/
    # train_step account for (nearly) all of the measured wall time.
    assert out["e2e_unexplained_fraction"] < 0.5


def test_feeder_e2e_crosscheck_fails_on_attribution_gap(
    feeder_ctx, monkeypatch,
):
    """The self-verification: if the attribution buckets stop seeing
    the loop's spans (renamed span, broken handoff, mapping rot), the
    scenario must fail rather than emit unattributable numbers."""
    from dss_ml_at_scale_tpu.bench import scenarios as scen_mod

    sc, ctx = feeder_ctx
    monkeypatch.setattr(
        scen_mod, "_attribution_buckets",
        lambda tail, since: {"data_wait": 0.0, "transfer": 0.0,
                             "compute": 0.0, "host": 0.0},
    )
    with pytest.raises(RuntimeError, match="unexplained"):
        sc.measure(ctx)


def test_mfu_gauges_priced_by_audit_pin():
    from dss_ml_at_scale_tpu.bench import mfu

    flops = mfu.pinned_flops("train_step.classifier")
    assert flops and flops > 0  # the audit baseline pins this program
    assert mfu.pinned_flops("no.such.entrypoint") is None

    block = mfu.publish_achieved(
        "train_step.classifier", 10.0, device_kind="TPU v4",
    )
    assert block["achieved_flops_per_sec"] == pytest.approx(flops * 10.0)
    assert block["utilization"] == pytest.approx(
        flops * 10.0 / mfu.PEAK_BF16_FLOPS["TPU v4"]
    )
    text = telemetry.render_prometheus()
    assert "entrypoint_achieved_flops_per_sec" in text
    assert "entrypoint_flops_utilization" in text
    assert mfu.publish_achieved("no.such.entrypoint", 10.0) is None


def test_mfu_publish_from_trace(tmp_path):
    from dss_ml_at_scale_tpu.bench import mfu

    def _tail(path, period):
        events = []
        for i in range(4):
            base = {"name": "train_step", "ts": i * period, "pid": 1,
                    "tid": 1, "trace": "t1", "span": f"{i:08x}",
                    "kind": "step"}
            events.append({**base, "ph": "B"})
            events.append({**base, "ph": "E", "dur": 0.5})
        path.write_text("\n".join(json.dumps(e) for e in events) + "\n")
        return path

    # Back-to-back spans: 4 steps over 2.0s of wall -> 2 steps/sec.
    block = mfu.publish_from_trace(
        _tail(tmp_path / "busy.jsonl", 0.5), "train_step.classifier"
    )
    assert block["steps_per_sec"] == pytest.approx(2.0)
    # Stalled run: same 0.5s spans arriving every 1.0s — the gaps ARE
    # wall time, so the rate halves (1/mean(dur) would still say 2.0
    # and inflate utilization exactly on the stalled runs).
    stalled = mfu.publish_from_trace(
        _tail(tmp_path / "stalled.jsonl", 1.0), "train_step.classifier"
    )
    assert stalled["steps_per_sec"] == pytest.approx(4 / 3.5, rel=1e-3)
    assert mfu.publish_from_trace(tmp_path / "empty.jsonl",
                                  "train_step.classifier") is None


def test_profile_merges_spans_and_profiler_events(tmp_path):
    """Acceptance gate: ONE Perfetto file holding both the
    flight-recorder spans (flow arrows intact) and the jax.profiler
    events of the same run."""
    from dss_ml_at_scale_tpu.bench.profile import (
        PROFILER_PID_OFFSET,
        profile_scenario,
    )

    out = tmp_path / "merged.json"
    report = profile_scenario("feeder_e2e", out, repetitions=1)
    assert report["spans"] > 0
    trace = json.loads(out.read_text())
    evs = trace["traceEvents"]
    dsst = [e for e in evs if e.get("pid", 0) < PROFILER_PID_OFFSET]
    prof = [e for e in evs if e.get("pid", 0) >= PROFILER_PID_OFFSET]
    # Host side: the runtime spans with their cross-thread flow arrows.
    names = {e["name"] for e in dsst if e.get("ph") == "X"}
    assert {"reader.next", "feeder.place", "train_step"} <= names
    assert any(e.get("ph") in ("s", "f") for e in dsst)
    # Device/profiler side: events present, pid-offset into their own
    # lanes, metadata labeled as jax.
    assert report["profiler_events"] == len(prof) > 0
    jax_lanes = [e for e in prof if e.get("ph") == "M"
                 and e.get("name") == "process_name"]
    assert jax_lanes and all(
        e["args"]["name"].startswith("jax: ") for e in jax_lanes
    )
    # Same timeline: profiler span timestamps overlap the host spans'
    # wall-clock window (epoch microseconds).
    host_ts = [e["ts"] for e in dsst if e.get("ph") == "X"]
    prof_ts = [e["ts"] for e in prof
               if e.get("ph") == "X" and e.get("ts")]
    assert prof_ts and host_ts
    assert min(prof_ts) < max(host_ts) and max(prof_ts) > min(host_ts)
    # The volume cap is explicit, never silent.
    assert "profiler_events_dropped" in report

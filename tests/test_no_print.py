"""Migrated into the ``dsst lint`` suite — see tests/test_lint.py
(rule ``no-print``). Kept as an import so external references break
neither collection nor muscle memory."""

from test_lint import test_no_print_clean  # noqa: F401

"""Tier-1 face of scripts/check_no_print.py: library code must not
print — everything goes through telemetry/tracking/logging; only the
CLI surface (config/) owns stdout."""

import importlib.util
from pathlib import Path


def _load_linter():
    path = Path(__file__).resolve().parents[1] / "scripts" / "check_no_print.py"
    spec = importlib.util.spec_from_file_location("check_no_print", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_no_bare_print_in_library():
    linter = _load_linter()
    violations = linter.find_violations()
    assert violations == [], "\n".join(violations)

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dss_ml_at_scale_tpu.runtime import (
    MeshSpec,
    Topology,
    batch_sharding,
    local_topology,
    make_mesh,
    replicated_sharding,
    shard_batch_to_mesh,
)


def test_default_mesh_spans_all_devices(devices8):
    mesh = make_mesh()
    assert mesh.axis_names == ("data",)
    assert mesh.devices.shape == (8,)


def test_mesh_spec_resolve():
    assert MeshSpec({"data": -1, "model": 2}).resolve(8) == {"data": 4, "model": 2}
    assert MeshSpec({"data": 8}).resolve(8) == {"data": 8}
    with pytest.raises(ValueError):
        MeshSpec({"data": 3}).resolve(8)
    with pytest.raises(ValueError):
        MeshSpec({"a": -1, "b": -1}).resolve(8)


def test_2d_mesh_and_collective(devices8):
    mesh = make_mesh({"data": 4, "model": 2})
    x = jax.device_put(jnp.arange(8.0).reshape(4, 2), NamedSharding(mesh, P("data", "model")))
    total = jax.jit(lambda v: v.sum())(x)
    assert float(total) == 28.0


def test_batch_sharding_places_batch_on_data_axis(devices8):
    mesh = make_mesh()
    batch = {"x": np.ones((16, 4), np.float32), "y": np.arange(16)}
    placed = shard_batch_to_mesh(batch, mesh)
    assert placed["x"].sharding.spec == P("data", None)
    assert placed["y"].sharding.spec == P("data")
    np.testing.assert_array_equal(np.asarray(placed["y"]), batch["y"])
    assert batch_sharding(mesh, ndim=2).spec == P("data", None)
    assert replicated_sharding(mesh).spec == P()


def test_topology_steps_per_epoch():
    topo = Topology(0, 1, 8, 8)
    # Mirrors rows // (batch * world): 10_000 // (212 * 8)
    assert topo.steps_per_epoch(10_000, 212) == 5
    assert topo.steps_per_epoch(10, 212) == 1  # floor at 1
    assert topo.global_batch_for(212) == 1696


def test_local_topology(devices8):
    topo = local_topology()
    assert topo.process_count == 1
    assert topo.global_device_count == 8
    assert topo.is_coordinator


def test_psum_over_data_axis(devices8):
    mesh = make_mesh()
    x = shard_batch_to_mesh(np.ones((8, 2), np.float32), mesh)

    @jax.jit
    def global_mean(v):
        return v.mean(axis=0)

    out = global_mean(x)
    np.testing.assert_allclose(np.asarray(out), np.ones(2))


def test_shard_batch_specs_validation(devices8):
    # The batch_specs override path must fail with the same clear
    # ValueError discipline as the default path: unknown mesh axis,
    # indivisible sharded dim — not an opaque XLA error downstream.
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from dss_ml_at_scale_tpu.runtime.mesh import make_mesh, shard_batch_to_mesh

    mesh = make_mesh({"data": 2, "sp": 4})
    ok = shard_batch_to_mesh(
        {"tokens": np.ones((8, 16, 3))}, mesh, axis="data",
        specs={"tokens": P(None, "sp")},
    )
    assert ok["tokens"].shape == (8, 16, 3)
    # Tuple-axis specs shard by the product of the named axes.
    ok2 = shard_batch_to_mesh(
        {"t": np.ones((16, 8))}, mesh, axis="data",
        specs={"t": P(("data", "sp"), None)},
    )
    assert ok2["t"].shape == (16, 8)

    with pytest.raises(ValueError, match="not in mesh axes"):
        shard_batch_to_mesh(
            {"tokens": np.ones((8, 16))}, mesh, axis="data",
            specs={"tokens": P(None, "bogus")},
        )
    with pytest.raises(ValueError, match="not divisible"):
        shard_batch_to_mesh(
            {"tokens": np.ones((8, 6))}, mesh, axis="data",
            specs={"tokens": P(None, "sp")},
        )


def test_check_same_mesh_rejects_reordered_devices(devices8):
    # Equal axis sizes are NOT enough: a different device assignment
    # would place state on one mesh while shard_map runs over another.
    import numpy as np
    import jax
    from jax.sharding import Mesh

    from dss_ml_at_scale_tpu.parallel.pipeline import check_same_mesh

    devs = np.array(jax.devices()).reshape(2, 4)
    m1 = Mesh(devs, ("pipe", "data"))
    check_same_mesh(m1, m1, "X")  # identity
    check_same_mesh(m1, Mesh(devs, ("pipe", "data")), "X")  # equal devices
    with pytest.raises(ValueError, match="construct the task"):
        check_same_mesh(m1, Mesh(devs[::-1], ("pipe", "data")), "X")
    with pytest.raises(ValueError, match="construct the task"):
        check_same_mesh(m1, Mesh(devs.reshape(4, 2), ("pipe", "data")), "X")

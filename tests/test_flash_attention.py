"""Flash attention kernel vs the XLA reference (Pallas interpret on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dss_ml_at_scale_tpu.ops import attention_reference, flash_attention


def _qkv(rng, b=1, h=2, s=256, d=64, dtype=jnp.float32):
    def mk():
        return jnp.asarray(rng.normal(size=(b, h, s, d)), dtype)

    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_matches_reference(rng, causal):
    q, k, v = _qkv(rng)
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_multiple_k_blocks_online_softmax(rng):
    # 4 k-blocks exercise the running-max/denominator rescaling path.
    q, k, v = _qkv(rng, s=256)
    out = flash_attention(q, k, v, block_q=64, block_k=64)
    ref = attention_reference(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_bf16_inputs(rng):
    q, k, v = _qkv(rng, s=128, dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True)
    ref = attention_reference(q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        out.astype(np.float32), ref.astype(np.float32), atol=2e-2
    )


def test_gradients_match_reference(rng):
    q, k, v = _qkv(rng, s=128, d=32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4)


def test_jit_and_small_seq_block_clamp(rng):
    # seq < block: blocks clamp to seq, still jittable.
    q, k, v = _qkv(rng, s=64, d=32)
    out = jax.jit(lambda q, k, v: flash_attention(q, k, v))(q, k, v)
    np.testing.assert_allclose(out, attention_reference(q, k, v), atol=2e-5)


def test_causal_cross_attention_bottom_right_aligned(rng):
    # Decode-with-cache shape: fewer queries than keys. Bottom-right
    # alignment means the last query row sees ALL keys.
    q, _, _ = _qkv(rng, s=64, d=32)
    _, k, v = _qkv(rng, s=256, d=32)
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    # Row parity with a manual full-context softmax for the last row.
    import math

    s_last = (q[0, 0, -1] @ k[0, 0].T) / math.sqrt(32)
    manual = jax.nn.softmax(s_last) @ v[0, 0]
    np.testing.assert_allclose(out[0, 0, -1], manual, atol=2e-5, rtol=2e-5)


def test_rejects_ragged_seq(rng):
    q, k, v = _qkv(rng, s=100)
    with pytest.raises(ValueError, match="multiples"):
        flash_attention(q, k, v, block_q=64, block_k=64)


def test_rejects_causal_sq_gt_sk(rng):
    # Bottom-right-aligned causal with sq > sk leaves the first sq - sk
    # query rows with zero visible keys (0/0 softmax); must be rejected.
    q, _, _ = _qkv(rng, s=64)
    _, k, v = _qkv(rng, s=32)
    with pytest.raises(ValueError, match="sq <= sk"):
        flash_attention(q, k, v, causal=True)

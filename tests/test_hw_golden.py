"""Golden-fixture parity tests for the Holt-Winters kernels.

The fixture (``tests/fixtures/hw_golden.json``, regenerate with
``python tests/fixtures/gen_hw_golden.py``) pins values from an
independent plain-NumPy oracle (explicit loop recursions, scipy Box-Cox
lambda, scipy bounded fits) for the four variants the reference's EDA
compares (``group_apply/02_Fine_Grained_Demand_Forecasting.py:143-188``).

Layers, strongest first:

1. **Recursion math** — at pinned smoothing parameters the ``lax.scan``
   recursion must reproduce the oracle's fitted values, SSE, and final
   states (both implement the declared heuristic two-season init, so
   this is tight f32-vs-f64 parity, not a modeling tolerance).
2. **Forecast math** — ``holt_winters_forecast`` from the oracle's final
   states must match the oracle's h-step forecasts (damped phi-sums,
   seasonal buffer indexing, mul vs add application).
3. **Box-Cox lambda** — golden-section MLE vs scipy Brent MLE.
4. **Fit quality** — ``holt_winters_fit``'s achieved SSE vs the oracle's
   multi-start scipy L-BFGS-B best (a stronger optimizer on the same
   surface, so a fair bar with stated slack).

The documented deviations from *statsmodels* (heuristic init, Box-Cox
clamp — ``ops/holt_winters.py:10-22``) don't enter here: the oracle pins
this implementation's declared semantics, independently re-implemented.
"""

import json
from pathlib import Path

import numpy as np
import pytest

import jax.numpy as jnp

from dss_ml_at_scale_tpu.ops import holt_winters_fit, holt_winters_forecast
from dss_ml_at_scale_tpu.ops.holt_winters import (
    _SEASONAL_CODES,
    HoltWintersResult,
    _heuristic_init,
    _smooth,
    boxcox_mle_lambda,
)

FIXTURE = Path(__file__).parent / "fixtures" / "hw_golden.json"


@pytest.fixture(scope="module")
def golden():
    fix = json.loads(FIXTURE.read_text())
    fix["_y"] = jnp.asarray(fix["y"], jnp.float32)
    return fix


def _variant_ids(fix_path=FIXTURE):
    return list(json.loads(fix_path.read_text())["variants"])


VARIANT_NAMES = _variant_ids()


@pytest.mark.parametrize("name", VARIANT_NAMES)
def test_recursion_matches_oracle_at_pinned_params(golden, name):
    var = golden["variants"][name]
    pin = var["pinned"]
    m = golden["m"]
    y = golden["_y"]
    init = _heuristic_init(y, m, var["seasonal"])
    params = (
        jnp.float32(pin["alpha"]), jnp.float32(pin["beta"]),
        jnp.float32(pin["gamma"]), jnp.float32(pin["phi"]),
    )
    sse, fitted, level, trend, season = _smooth(
        y, params, init, m, var["seasonal"], var["damped"]
    )
    np.testing.assert_allclose(
        np.asarray(fitted), np.asarray(var["fitted"]), rtol=2e-4, atol=2e-2
    )
    assert float(sse) == pytest.approx(var["sse"], rel=2e-4)
    assert float(level) == pytest.approx(var["level"], rel=2e-4)
    assert float(trend) == pytest.approx(var["trend"], rel=2e-3, abs=1e-3)
    np.testing.assert_allclose(
        np.asarray(season), np.asarray(var["season"]), rtol=2e-3, atol=2e-3
    )


@pytest.mark.parametrize("name", VARIANT_NAMES)
def test_forecast_matches_oracle_from_pinned_states(golden, name):
    var = golden["variants"][name]
    pin = var["pinned"]
    result = HoltWintersResult(
        alpha=jnp.float32(pin["alpha"]),
        beta=jnp.float32(pin["beta"]),
        gamma=jnp.float32(pin["gamma"]),
        phi=jnp.float32(pin["phi"]),
        boxcox_lambda=jnp.float32(1.0),
        use_boxcox=jnp.asarray(False),
        seasonal_code=jnp.asarray(_SEASONAL_CODES[var["seasonal"]], jnp.int32),
        level=jnp.float32(var["level"]),
        trend=jnp.float32(var["trend"]),
        season=jnp.asarray(var["season"], jnp.float32),
        fittedvalues=jnp.zeros(1),
        sse=jnp.float32(0.0),
    )
    fc = holt_winters_forecast(result, golden["h_max"])
    np.testing.assert_allclose(
        np.asarray(fc), np.asarray(var["forecast"]), rtol=5e-4, atol=5e-2
    )


def test_boxcox_lambda_matches_scipy_mle(golden):
    assert golden["boxcox_lambda_interior"], (
        "fixture series' scipy MLE lambda left the [-1, 2] search bracket; "
        "regenerate with a different series"
    )
    lam = float(boxcox_mle_lambda(golden["_y"]))
    assert lam == pytest.approx(golden["boxcox_lambda"], abs=0.05)


@pytest.mark.slow
@pytest.mark.parametrize("name", VARIANT_NAMES)
def test_fit_quality_vs_oracle_best(golden, name):
    var = golden["variants"][name]
    res = holt_winters_fit(
        golden["_y"], golden["m"], seasonal=var["seasonal"],
        damped=var["damped"], use_boxcox=False, max_iter=600,
    )
    # Oracle best comes from multi-start bounded L-BFGS-B (f64); the f32
    # Nelder-Mead must land within 5% SSE of it.
    assert float(res.sse) <= var["best_sse"] * 1.05
    assert np.isfinite(np.asarray(res.fittedvalues)).all()


@pytest.mark.slow
def test_boxcox_fit_estimates_fixture_lambda(golden):
    res = holt_winters_fit(
        golden["_y"], golden["m"], seasonal="add", damped=False,
        use_boxcox=True, max_iter=400,
    )
    assert float(res.boxcox_lambda) == pytest.approx(
        golden["boxcox_lambda"], abs=0.1
    )
    assert np.isfinite(np.asarray(res.fittedvalues)).all()
    assert np.isfinite(float(res.sse))

import numpy as np
import pytest

from dss_ml_at_scale_tpu.hpo import (
    STATUS_FAIL,
    STATUS_OK,
    TPE,
    Trials,
    fmin,
    hp,
    random_suggest,
    sample_space,
    space_eval,
    tpe_suggest,
)
from dss_ml_at_scale_tpu.hpo.hp import scope
from dss_ml_at_scale_tpu.hpo.shipping import (
    Broadcast,
    broadcast,
    load_shared,
    save_shared,
)


# -- spaces ------------------------------------------------------------------


def test_space_sampling_ranges():
    rng = np.random.default_rng(0)
    space = {
        "u": hp.uniform("u", -1, 1),
        "lu": hp.loguniform("lu", 1e-3, 1e2),
        "ln": hp.lognormal("ln", 0, 1),
        "q": scope.int(hp.quniform("q", 0, 4, 1)),
        "c": hp.choice("c", ["a", "b", "c"]),
    }
    for _ in range(200):
        pt = sample_space(space, rng)
        assert -1 <= pt["u"] <= 1
        assert 1e-3 <= pt["lu"] <= 1e2
        assert pt["ln"] > 0
        assert pt["q"] in (0, 1, 2, 3, 4) and isinstance(pt["q"], int)
        assert pt["c"] in (0, 1, 2)


def test_space_eval_structure():
    space = {
        "order": (
            scope.int(hp.quniform("p", 0, 4, 1)),
            scope.int(hp.quniform("d", 0, 2, 1)),
            scope.int(hp.quniform("q", 0, 4, 1)),
        ),
        "trend": hp.choice("trend", ["n", "c", "t"]),
        "fixed": 42,
    }
    point = {"p": 2, "d": 1, "q": 3, "trend": 1}
    out = space_eval(space, point)
    assert out == {"order": (2, 1, 3), "trend": "c", "fixed": 42}


def test_duplicate_labels_rejected():
    with pytest.raises(ValueError, match="duplicate"):
        sample_space(
            [hp.uniform("x", 0, 1), hp.uniform("x", 5, 6)], np.random.default_rng(0)
        )


def test_seeded_sampling_deterministic():
    space = {"x": hp.uniform("x", 0, 1), "c": hp.choice("c", [1, 2, 3])}
    a = [sample_space(space, np.random.default_rng(42)) for _ in range(3)]
    assert a[0] == a[1] == a[2]


# -- fmin / Trials -----------------------------------------------------------


def test_fmin_sequential_quadratic():
    best = fmin(
        lambda p: (p["x"] - 3.0) ** 2,
        {"x": hp.uniform("x", -10, 10)},
        max_evals=60,
        rstate=0,
    )
    assert abs(best["x"] - 3.0) < 0.5


def test_fmin_reproducible_with_seed():
    space = {"x": hp.uniform("x", -5, 5)}
    obj = lambda p: (p["x"] + 1) ** 2
    b1 = fmin(obj, space, max_evals=25, rstate=7)
    b2 = fmin(obj, space, max_evals=25, rstate=7)
    assert b1 == b2


def test_tpe_beats_random_on_quadratic():
    space = {"x": hp.uniform("x", -10, 10), "y": hp.uniform("y", -10, 10)}
    obj = lambda p: (p["x"] - 2) ** 2 + (p["y"] + 4) ** 2

    def best_loss(algo, seed):
        t = fmin(obj, space, algo=algo, max_evals=50, rstate=seed, return_argmin=False)
        return min(l for l in t.losses if l is not None)

    tpe_scores = [best_loss(tpe_suggest, s) for s in range(5)]
    rnd_scores = [best_loss(random_suggest, s) for s in range(5)]
    assert np.mean(tpe_scores) < np.mean(rnd_scores)


def test_failed_trials_are_isolated():
    calls = {"n": 0}

    def flaky(p):
        calls["n"] += 1
        if p["x"] < 0:
            raise RuntimeError("negative!")
        return p["x"] ** 2

    trials = fmin(
        flaky,
        {"x": hp.uniform("x", -1, 1)},
        max_evals=30,
        rstate=3,
        return_argmin=False,
    )
    statuses = {t["result"]["status"] for t in trials.trials}
    assert STATUS_FAIL in statuses and STATUS_OK in statuses
    assert len(trials.trials) == 30  # sweep completed despite failures
    assert calls["n"] == 30
    assert trials.best_trial["result"]["loss"] >= 0
    fail = next(t for t in trials.trials if t["result"]["status"] == STATUS_FAIL)
    assert "negative!" in fail["result"]["error"]


def test_objective_dict_protocol():
    def obj(p):
        return {"loss": p["x"] ** 2, "status": STATUS_OK, "extra": "kept"}

    trials = fmin(
        obj, {"x": hp.uniform("x", -2, 2)}, max_evals=12, rstate=0, return_argmin=False
    )
    assert trials.best_trial["result"]["extra"] == "kept"


def test_choice_param_in_fmin():
    # minimum at kernel="b"
    table = {"a": 3.0, "b": 0.5, "c": 2.0}
    best = fmin(
        lambda p: table[p["kernel"]],
        {"kernel": hp.choice("kernel", ["a", "b", "c"])},
        max_evals=25,
        rstate=0,
    )
    assert best["kernel"] == 1  # index, like hyperopt argmin


# -- distributed executor ----------------------------------------------------


def test_device_trials_parallel_sweep(devices8):
    from dss_ml_at_scale_tpu.parallel import DeviceTrials

    seen = []
    lock = __import__("threading").Lock()

    def obj(p):
        import jax.numpy as jnp

        val = float(jnp.asarray(p["x"]) ** 2)  # touches the pinned device
        with lock:
            seen.append(p["x"])
        return val

    trials = DeviceTrials(parallelism=4)
    best = fmin(obj, {"x": hp.uniform("x", -3, 3)}, max_evals=20,
                trials=trials, rstate=0)
    assert len(trials.trials) == 20
    assert len(seen) == 20
    assert [t["tid"] for t in trials.trials] == list(range(20))
    assert abs(best["x"]) < 1.5


def test_device_trials_failure_isolation(devices8):
    from dss_ml_at_scale_tpu.parallel import DeviceTrials

    def obj(p):
        if p["x"] > 0:
            raise ValueError("boom")
        return -p["x"]

    trials = DeviceTrials(parallelism=3)
    fmin(obj, {"x": hp.uniform("x", -1, 1)}, max_evals=15, trials=trials, rstate=1)
    assert len(trials.trials) == 15
    assert any(t["result"]["status"] == STATUS_FAIL for t in trials.trials)
    assert trials.best_trial["result"]["loss"] >= 0


def test_device_trials_max_concurrency(devices8):
    import threading

    from dss_ml_at_scale_tpu.parallel import DeviceTrials

    state = {"now": 0, "peak": 0}
    lock = threading.Lock()

    def obj(p):
        with lock:
            state["now"] += 1
            state["peak"] = max(state["peak"], state["now"])
        import time

        time.sleep(0.02)
        with lock:
            state["now"] -= 1
        return p["x"] ** 2

    fmin(
        obj,
        {"x": hp.uniform("x", -1, 1)},
        max_evals=12,
        trials=DeviceTrials(parallelism=3, pin_devices=False),
        rstate=0,
    )
    assert state["peak"] <= 3


# -- data shipping -----------------------------------------------------------


def test_broadcast_lazy_and_shared():
    builds = {"n": 0}

    def factory():
        builds["n"] += 1
        return np.arange(10)

    b = Broadcast(factory=factory)
    assert builds["n"] == 0
    np.testing.assert_array_equal(b.value, np.arange(10))
    b.value
    assert builds["n"] == 1
    assert broadcast([1, 2]).value == [1, 2]
    with pytest.raises(ValueError):
        Broadcast()


def test_shared_fs_roundtrip(tmp_path):
    x = np.random.default_rng(0).normal(size=(100, 5))
    y = np.arange(100)
    path = save_shared(tmp_path / "data.npz", X=x, y=y)
    out = load_shared(path)
    np.testing.assert_array_equal(out["X"], x)
    np.testing.assert_array_equal(out["y"], y)
    # cached: same dict object back
    assert load_shared(path) is out


def test_loguniform_bounds_validated():
    with pytest.raises(ValueError, match="low > 0"):
        hp.loguniform("x", 0, 10)


def test_malformed_result_fails_trial_not_sweep():
    out = fmin(
        lambda p: {"loss": "bad", "status": STATUS_OK},
        {"x": hp.uniform("x", 0, 1)},
        max_evals=3,
        rstate=0,
        return_argmin=False,
    )
    assert all(t["result"]["status"] == STATUS_FAIL for t in out.trials)


def test_randint_uniform_endpoints():
    rng = np.random.default_rng(0)
    draws = [sample_space({"k": hp.randint("k", 3)}, rng)["k"] for _ in range(3000)]
    counts = np.bincount(draws, minlength=3) / 3000
    assert np.all(np.abs(counts - 1 / 3) < 0.05), counts


def test_device_trials_resume_keeps_pinning(devices8):
    from dss_ml_at_scale_tpu.parallel import DeviceTrials

    dt = DeviceTrials(parallelism=2)
    fmin(lambda p: p["x"] ** 2, {"x": hp.uniform("x", -1, 1)}, max_evals=4,
         trials=dt, rstate=0)
    fmin(lambda p: p["x"] ** 2, {"x": hp.uniform("x", -1, 1)}, max_evals=10,
         trials=dt, rstate=1)
    assert [t["tid"] for t in dt.trials] == list(range(10))


def test_unpersist_semantics():
    with pytest.raises(ValueError, match="value-backed"):
        broadcast([1]).unpersist()
    b = Broadcast(factory=lambda: [1, 2])
    assert b.value == [1, 2]
    b.unpersist()
    assert b.value == [1, 2]  # rebuilt


def test_fmin_nonfinite_loss_is_isolated():
    # A diverged trial (NaN loss) must fail that trial, not win argmin.
    from itertools import count

    calls = count()

    def obj(p):
        return float("nan") if next(calls) == 0 else (p["x"] - 2.0) ** 2

    from dss_ml_at_scale_tpu.hpo import Trials, fmin, hp

    trials = Trials()
    best = fmin(obj, {"x": hp.uniform("x", 0, 5)}, max_evals=15, trials=trials, rstate=0)
    assert abs(best["x"] - 2.0) < 1.5
    assert sum(r["status"] == "fail" for r in trials.results) == 1


def test_two_device_trials_smoke_logic(tmp_path, monkeypatch, devices8):
    # The on-chip 2-device smoke's pass-path logic, driven on the
    # simulated slice: two pinned trials must use distinct devices and
    # genuinely overlap. On real hardware the driver runs the script via
    # run_tpu_artifacts.sh with the cpu guard active.
    monkeypatch.setenv("DSST_SMOKE_ALLOW_CPU", "1")
    monkeypatch.chdir(tmp_path)
    import smoke_two_device_trials as smoke

    assert smoke.main() == 0
    import json

    out = json.loads((tmp_path / "TRIALS_2DEV.json").read_text())
    assert out["passed"] is True
    assert out["trials_ok"] == 8
    assert len(out["distinct_devices_used"]) >= 2
    assert out["max_concurrent"] >= 2

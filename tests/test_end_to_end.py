"""The minimum end-to-end slice (SURVEY.md §7): Delta table of JPEGs →
sharded streaming decode → jitted DP training on an 8-device mesh."""

import io

import numpy as np
import optax
import pyarrow as pa
import pytest
from PIL import Image

from dss_ml_at_scale_tpu.data import batch_loader, write_delta, DeltaTable
from dss_ml_at_scale_tpu.data.transform import imagenet_transform_spec
from dss_ml_at_scale_tpu.parallel import ClassifierTask, Trainer, TrainerConfig
from dss_ml_at_scale_tpu.runtime import make_mesh

from test_models import tiny_resnet


def _jpeg(rng, bright_quadrant):
    img = (rng.normal(0.3, 0.05, (64, 64, 3)) * 255).clip(0, 255)
    r, c = divmod(int(bright_quadrant), 2)
    img[r * 32 : (r + 1) * 32, c * 32 : (c + 1) * 32] = 240
    buf = io.BytesIO()
    Image.fromarray(img.astype(np.uint8)).save(buf, format="JPEG")
    return buf.getvalue()


@pytest.fixture(scope="module")
def image_delta_table(tmp_path_factory):
    rng = np.random.default_rng(0)
    labels = rng.integers(0, 4, 128)
    table = pa.table(
        {
            "content": pa.array([_jpeg(rng, l) for l in labels], type=pa.binary()),
            "label_index": pa.array(labels.astype(np.int64)),
        }
    )
    path = tmp_path_factory.mktemp("delta") / "imagenet_mini"
    write_delta(table, path, max_rows_per_file=16)
    return path


def test_end_to_end_training_slice(devices8, image_delta_table):
    dt = DeltaTable(image_delta_table)
    rows = dt.num_records()
    assert rows == 128

    mesh = make_mesh()
    batch_size = 16
    spec = imagenet_transform_spec(crop=64)
    task = ClassifierTask(
        model=tiny_resnet(num_classes=4), tx=optax.adam(1e-2)
    )
    trainer = Trainer(
        TrainerConfig(
            max_epochs=3,
            total_train_rows=rows,
            limit_val_batches=2,
            log_every_steps=4,
        ),
        mesh=mesh,
    )
    with batch_loader(
        dt,
        batch_size=batch_size,
        num_epochs=None,          # infinite; epochs drawn by step count
        workers_count=2,
        results_queue_size=4,
        transform_spec=spec,
    ) as train_reader:
        result = trainer.fit(
            task,
            train_reader,
            val_data_factory=lambda: batch_loader(
                dt, batch_size=batch_size, num_epochs=1,
                transform_spec=spec, shuffle_row_groups=False,
            ).__enter__(),
        )
    # 128 rows // 16 = 8 steps/epoch × 3 epochs
    assert int(result.state.step) == 24
    # Epoch summaries carry the LAST step's metrics, which are one-batch
    # noisy (the reader shuffles row groups nondeterministically) — so
    # accept either signal of learning: loss below epoch 0's, or the
    # quadrant task solved well above chance (0.25).
    assert (
        result.history[-1]["train_loss"] < result.history[0]["train_loss"]
        or result.history[-1]["train_acc"] >= 0.75
    ), result.history
    assert "val_acc" in result.history[-1]
    assert result.history[-1]["images_per_sec"] > 0


def test_end_to_end_health_rollback_parity(devices8, image_delta_table, tmp_path):
    """The PR-4 acceptance slice with the REAL reader in the loop: a
    grads.nonfinite fault injected at step 2 under --health-policy
    rollback discards the update and quarantines the batch's rows; a
    clean replay whose reader consults the blocklist produces
    bitwise-identical final params. Row-exact reader exclusion + the
    on-device discard select are what make the two runs see the same
    update sequence."""
    from dss_ml_at_scale_tpu.resilience import FaultPlan, QuarantineList, faults
    from dss_ml_at_scale_tpu.resilience.health import HealthConfig

    dt = DeltaTable(image_delta_table)
    mesh = make_mesh()
    spec = imagenet_transform_spec(crop=64)
    quarantine_file = tmp_path / "quarantine.jsonl"

    def run(*, poison: bool):
        task = ClassifierTask(
            model=tiny_resnet(num_classes=4), tx=optax.adam(1e-2)
        )
        health = HealthConfig(
            policy="rollback", quarantine=QuarantineList(quarantine_file)
        )
        trainer = Trainer(
            TrainerConfig(
                max_epochs=1, steps_per_epoch=4, log_every_steps=100,
                health=health,
            ),
            mesh=mesh,
        )
        if poison:
            faults.install(FaultPlan.parse("grads.nonfinite=1@1"))
        try:
            # One worker + no shuffle: deterministic arrival order, so
            # the two runs' surviving row streams align batch-for-batch.
            with batch_loader(
                dt, batch_size=16, num_epochs=None, workers_count=1,
                transform_spec=spec, shuffle_row_groups=False,
                quarantine=QuarantineList(quarantine_file),
                emit_provenance=True, on_corrupt="quarantine",
            ) as reader:
                return trainer.fit(task, reader)
        finally:
            faults.clear()

    poisoned = run(poison=True)
    assert int(poisoned.state.step) == 4 and poisoned.skipped_steps == 1
    q = QuarantineList(quarantine_file)
    assert len(q) == 1
    assert q.entries[0]["row_hi"] - q.entries[0]["row_lo"] == 16

    clean = run(poison=False)  # reader consults the blocklist on replay
    assert int(clean.state.step) == 4 and clean.skipped_steps == 0
    import jax

    for a, b in zip(
        jax.tree_util.tree_leaves(poisoned.state.params),
        jax.tree_util.tree_leaves(clean.state.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

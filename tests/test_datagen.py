"""Tests for the synthetic data generators (demand / BoM / regression)."""

import numpy as np
import pandas as pd
import pytest

from dss_ml_at_scale_tpu.data.delta import DeltaTable
from dss_ml_at_scale_tpu.datagen import (
    DemandConfig,
    gen_data,
    generate_bom,
    generate_demand,
    product_hierarchy,
    train_and_eval,
    tune_alpha,
    weekly_date_spine,
    write_bom_delta,
    write_demand_delta,
)

CFG = DemandConfig(n_skus_per_product=2)  # 10 SKUs: fast but full-structure


def test_weekly_date_spine_structure():
    spine = weekly_date_spine(CFG)
    # 3y × 52 weeks inclusive endpoints = 157 Mondays (reference :135-145).
    assert len(spine) == 157
    dates = pd.to_datetime(spine["Date"])
    assert (dates.dt.weekday == 0).all()
    assert dates.iloc[-1] == pd.Timestamp("2021-07-19")
    # COVID factor: 1.0 before breakpoint, ramp (100-20)/100 -> (100-7)/100.
    pre = spine[spine["Corona_Breakpoint_Helper"] == 0]
    assert (pre["Corona_Factor"] == 1.0).all()
    post = spine[spine["Corona_Breakpoint_Helper"] > 0]
    assert abs(post["Corona_Factor"].min() - 0.80) < 0.02
    assert abs(post["Corona_Factor"].iloc[-1] - 0.93) < 0.005
    # Christmas/New-Year factors down in w51/52, up in w1-4 (reference :161-181).
    assert (spine.loc[spine["Week"] == 52, "Factor_XMas"] == 0.8).all()
    assert (spine.loc[spine["Week"] == 2, "Factor_XMas"] == 1.15).all()


def test_product_hierarchy_shape_and_determinism():
    h1, h2 = product_hierarchy(CFG), product_hierarchy(CFG)
    assert len(h1) == 10 and h1["SKU"].nunique() == 10
    assert (h1["SKU"].str.len() == 10).all()  # PREFIX_ + 6 chars
    pd.testing.assert_frame_equal(h1, h2)


def test_generate_demand_panel():
    df = generate_demand(CFG)
    assert len(df) == 10 * 157  # row-count invariant (reference :125)
    assert list(df.columns) == ["Product", "SKU", "Date", "Demand"]
    assert np.isfinite(df["Demand"]).all()
    assert (df["Demand"] == np.round(df["Demand"])).all()  # rounded (:305)
    # Per-SKU series must differ (the deliberate fix over the reference's
    # per-group reseeding) and sit near their product offset (>= 4000-ish).
    by_sku = df.groupby("SKU")["Demand"].mean()
    assert by_sku.min() > 1000
    assert df.groupby("SKU")["Demand"].first().nunique() > 5
    # Christmas dip: week-52 demand below the adjacent non-holiday weeks.
    spine = weekly_date_spine(CFG)
    w52 = set(spine.loc[spine["Week"] == 52, "Date"])
    one = df[df["SKU"] == df["SKU"].iloc[0]].reset_index(drop=True)
    idx = one.index[one["Date"].isin(w52)]
    for i in idx:
        if 2 <= i < len(one) - 2:
            neighborhood = one["Demand"].iloc[[i - 2, i + 2]].mean()
            assert one["Demand"].iloc[i] < neighborhood


def test_demand_delta_roundtrip(tmp_path):
    df = generate_demand(CFG)
    path = tmp_path / "part_level_demand"
    write_demand_delta(df, path)
    table = DeltaTable(path)
    assert table.num_records() == len(df)


def test_generate_bom_structure():
    skus = list(product_hierarchy(CFG)["SKU"])
    tables = generate_bom(skus)
    import networkx as nx

    assert nx.is_directed_acyclic_graph(tables.graph)
    # Every SKU reachable via exactly one head edge in the mapper.
    assert set(tables.sku_mapper["sku"]) == set(skus)
    assert len(tables.sku_mapper) == len(skus)
    # Edges into SKUs carry qty 1; bom quantities in 1-3 (reference :468-469).
    assert (tables.bom["qty"].isin([1, 2, 3])).all()
    assert not tables.bom["material_out"].str.match("SRL|LRL|CAM|SRR|LRR_.*").any()
    # 3 levels: head + 2 expansion levels with fan-out 2-4, <=3 extended.
    g = tables.graph
    sku0 = skus[0]
    heads = list(g.predecessors(sku0))
    assert len(heads) == 1
    level2 = list(g.predecessors(heads[0]))
    assert 2 <= len(level2) <= 4
    # Determinism
    t2 = generate_bom(skus)
    pd.testing.assert_frame_equal(tables.bom, t2.bom)


def test_bom_delta_roundtrip(tmp_path):
    skus = list(product_hierarchy(CFG)["SKU"])
    tables = generate_bom(skus)
    write_bom_delta(tables, tmp_path / "bom", tmp_path / "sku_mapper")
    assert DeltaTable(tmp_path / "bom").num_records() == len(tables.bom)
    assert DeltaTable(tmp_path / "sku_mapper").num_records() == len(tables.sku_mapper)


def test_gen_data_sizing_and_tune():
    data = gen_data(1_000_000)
    X_train, X_test, y_train, y_test = data
    total = sum(a.nbytes for a in (X_train, X_test, y_train, y_test))
    assert abs(total - 1_000_000) / 1_000_000 < 0.05
    out = train_and_eval(data, alpha=0.5)
    assert out["status"] == "ok" and np.isfinite(out["loss"])
    best_alpha = tune_alpha(lambda a: train_and_eval(data, a), max_evals=4)
    assert 0.0 <= best_alpha <= 10.0


# ---------------------------------------------------------------------------
# Synthetic Markov token streams (LM track fixture)
# ---------------------------------------------------------------------------

def test_token_stream_shapes_and_determinism():
    from dss_ml_at_scale_tpu.datagen.tokens import (
        TokenStreamConfig,
        entropy_floor,
        token_batches,
        transition_matrix,
    )

    cfg = TokenStreamConfig(vocab_size=32, batch_size=4, seq_len=16, seed=7)
    t = transition_matrix(cfg)
    assert t.shape == (32, 32)
    np.testing.assert_allclose(t.sum(axis=1), 1.0, atol=1e-12)

    a = [b["tokens"].copy() for b in token_batches(cfg, num_batches=3)]
    b = [b["tokens"].copy() for b in token_batches(cfg, num_batches=3)]
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)  # seeded stream
        assert x.shape == (4, 16) and x.dtype == np.int32
        assert x.min() >= 0 and x.max() < 32

    # Peaky rows (low concentration) must give a lower entropy floor than
    # near-uniform rows, and both sit inside [0, log V].
    lo = entropy_floor(TokenStreamConfig(vocab_size=32, concentration=0.02))
    hi = entropy_floor(TokenStreamConfig(vocab_size=32, concentration=50.0))
    assert 0.0 < lo < hi < np.log(32) + 1e-9


def test_token_stream_is_learnable_markov():
    # The empirical bigram distribution of a long stream must match the
    # chain's transition matrix — i.e. the data really is the chain.
    from dss_ml_at_scale_tpu.datagen.tokens import (
        TokenStreamConfig,
        token_batches,
        transition_matrix,
    )

    cfg = TokenStreamConfig(
        vocab_size=8, batch_size=16, seq_len=512, concentration=0.3, seed=3
    )
    t = transition_matrix(cfg)
    counts = np.zeros((8, 8))
    for batch in token_batches(cfg, num_batches=4):
        toks = batch["tokens"]
        for row in toks:
            np.add.at(counts, (row[:-1], row[1:]), 1.0)
    empirical = counts / np.maximum(counts.sum(axis=1, keepdims=True), 1.0)
    visited = counts.sum(axis=1) > 200
    assert visited.any()
    np.testing.assert_allclose(
        empirical[visited], t[visited], atol=0.08
    )

"""Tier-1 face of scripts/check_fault_sites.py: every fault-injection
site used in the package is registered/documented in
resilience.faults.KNOWN_SITES, and no registered site is dead."""

import importlib.util
from pathlib import Path

import pytest


def _load_linter():
    path = (
        Path(__file__).resolve().parents[1]
        / "scripts" / "check_fault_sites.py"
    )
    spec = importlib.util.spec_from_file_location("check_fault_sites", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_fault_sites_registry_matches_call_sites():
    linter = _load_linter()
    violations = linter.find_violations()
    assert violations == [], "\n".join(violations)


@pytest.fixture()
def linter():
    return _load_linter()


def test_lint_flags_unregistered_site(tmp_path, linter):
    (tmp_path / "mod.py").write_text(
        "from resilience.faults import maybe_fail\n"
        'maybe_fail("totally.new.site")\n'
    )
    violations = linter.find_violations(tmp_path, known={"reader.next": "x"})
    assert len(violations) == 2  # unregistered site + dead registry key
    assert "totally.new.site" in violations[0]
    assert "reader.next" in violations[1]


def test_lint_flags_non_literal_site_outside_wrappers(tmp_path, linter):
    (tmp_path / "mod.py").write_text(
        "def f(site):\n"
        "    maybe_fail(site)\n"  # not a registered wrapper name
    )
    violations = linter.find_violations(tmp_path, known={})
    assert violations and "non-literal" in violations[0]


def test_lint_allows_fstring_prefix_and_forwarding_wrapper(tmp_path, linter):
    (tmp_path / "mod.py").write_text(
        "def _maybe_fail(site):\n"
        "    maybe_fail(site)\n"     # forwarding wrapper: allowed
        "def send(method):\n"
        '    _maybe_fail(f"rpc.send.{method}")\n'
    )
    violations = linter.find_violations(
        tmp_path, known={"rpc.send": "transport"}
    )
    assert violations == [], "\n".join(violations)

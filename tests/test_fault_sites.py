"""Migrated into the ``dsst lint`` suite — see tests/test_lint.py
(rule ``fault-sites``). Kept as an import so external references break
neither collection nor muscle memory."""

from test_lint import test_fault_sites_clean  # noqa: F401

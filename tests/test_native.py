"""Native C++ image pipeline vs the pure-Python (PIL) reference path."""

import io

import numpy as np
import pytest

from dss_ml_at_scale_tpu import native
from dss_ml_at_scale_tpu.data.transform import (
    IMAGENET_MEAN,
    IMAGENET_STD,
    decode_resize_crop,
    imagenet_transform_spec,
)

pytestmark = pytest.mark.skipif(
    not native.native_available(), reason=native.load_error() or "no native lib"
)


def _jpeg(rng, w, h, mode="RGB", quality=95) -> bytes:
    from PIL import Image

    arr = rng.integers(0, 256, (h, w, 3), dtype=np.uint8)
    img = Image.fromarray(arr, "RGB").convert(mode)
    buf = io.BytesIO()
    img.save(buf, "JPEG", quality=quality)
    return buf.getvalue()


def test_native_matches_pil(rng):
    jpegs = [_jpeg(rng, w, h) for w, h in [(320, 240), (240, 320), (500, 375), (224, 224)]]
    images, ok = native.decode_jpeg_batch(jpegs, resize=256, crop=224)
    assert ok.all()
    assert images.shape == (4, 3, 224, 224)
    for i, b in enumerate(jpegs):
        ref = decode_resize_crop(b, resize=256, crop=224)
        # Same decode, same antialiased triangle resize; differences come
        # from PIL's per-pass uint8 quantization vs float intermediates.
        assert np.mean(np.abs(images[i] - ref)) < 0.01
        assert np.max(np.abs(images[i] - ref)) < 0.15


def test_native_normalize_fused(rng):
    jpegs = [_jpeg(rng, 300, 280)]
    raw, _ = native.decode_jpeg_batch(jpegs)
    normed, _ = native.decode_jpeg_batch(jpegs, mean=IMAGENET_MEAN, std=IMAGENET_STD)
    want = (raw[0] - IMAGENET_MEAN[:, None, None]) / IMAGENET_STD[:, None, None]
    np.testing.assert_allclose(normed[0], want, atol=1e-5)


def test_native_grayscale_and_hwc(rng):
    jpegs = [_jpeg(rng, 256, 256, mode="L")]
    images, ok = native.decode_jpeg_batch(jpegs, chw=False)
    assert ok.all()
    assert images.shape == (1, 224, 224, 3)
    # Grayscale upconvert: all channels equal.
    np.testing.assert_allclose(images[0, ..., 0], images[0, ..., 1], atol=1e-6)


def test_corrupt_jpeg_flagged_not_fatal(rng):
    good = _jpeg(rng, 260, 260)
    images, ok = native.decode_jpeg_batch([good, b"not a jpeg", good[:50]])
    assert ok.tolist() == [True, False, False]
    assert np.all(images[1] == 0)


def test_transform_spec_native_backend_matches_pil(rng):
    jpegs = [_jpeg(rng, 320, 260) for _ in range(3)]
    batch = {
        "content": np.array(jpegs, dtype=object),
        "label_index": np.array([1, 2, 3]),
    }
    out_native = imagenet_transform_spec(backend="native")(batch)
    out_pil = imagenet_transform_spec(backend="pil")(batch)
    assert out_native["image"].shape == (3, 224, 224, 3)
    assert np.mean(np.abs(out_native["image"] - out_pil["image"])) < 0.05
    np.testing.assert_array_equal(out_native["label"], out_pil["label"])


def test_auto_backend_falls_back_per_image(rng):
    # CMYK JPEGs are rejected by the native decoder; auto backend must
    # transparently re-decode those rows with PIL.
    good = _jpeg(rng, 300, 300)
    cmyk = _jpeg(rng, 300, 300, mode="CMYK")
    batch = {
        "content": np.array([good, cmyk], dtype=object),
        "label_index": np.array([0, 1]),
    }
    out = imagenet_transform_spec(backend="auto")(batch)
    ref = imagenet_transform_spec(backend="pil")(batch)
    assert np.mean(np.abs(out["image"][1] - ref["image"][1])) < 0.05


def test_fast_scale_decodes_close_to_full(rng):
    # DCT-scaled decode (PIL draft equivalent) is a different pixel path;
    # it must stay visually equivalent (small mean abs diff) and shape-
    # identical, with small sources (min side <= resize) untouched.
    big = _jpeg(rng, 1024, 768)
    full, ok1 = native.decode_jpeg_batch([big], chw=False)
    fast, ok2 = native.decode_jpeg_batch([big], chw=False, fast_scale=True)
    assert ok1.all() and ok2.all()
    assert full.shape == fast.shape
    assert np.mean(np.abs(full - fast)) < 0.03  # [0,1] scale

    small = _jpeg(rng, 240, 230)  # min side < resize: no DCT scaling
    a, _ = native.decode_jpeg_batch([small], chw=False)
    b, _ = native.decode_jpeg_batch([small], chw=False, fast_scale=True)
    np.testing.assert_array_equal(a, b)


def test_transform_spec_fast_decode(rng):
    jpegs = [_jpeg(rng, 800, 600) for _ in range(2)]
    batch = {
        "content": np.array(jpegs, dtype=object),
        "label_index": np.array([0, 1]),
    }
    out = imagenet_transform_spec(backend="native", fast_decode=True)(batch)
    ref = imagenet_transform_spec(backend="native")(batch)
    assert out["image"].shape == ref["image"].shape
    # Normalized space: tolerate the draft-mode deviation, reject garbage.
    assert np.mean(np.abs(out["image"] - ref["image"])) < 0.15

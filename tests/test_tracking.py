import json

import pytest

from dss_ml_at_scale_tpu.tracking import RunStore, start_run


def test_run_store_roundtrip(tmp_path):
    store = RunStore(tmp_path, "exp1", run_name="my-run")
    store.log_params({"lr": 1e-5, "batch": 212, "obj": {"a": 1}})
    store.log_metrics({"loss": 2.5}, step=1)
    store.log_metrics({"loss": 1.5, "acc": 0.7}, step=2)
    store.finish()

    assert store.params()["lr"] == 1e-5
    ms = store.metrics()
    assert [m["value"] for m in ms if m["name"] == "loss"] == [2.5, 1.5]
    meta = json.loads((store.path / "meta.json").read_text())
    assert meta["status"] == "FINISHED"
    assert meta["run_name"] == "my-run"


def test_start_run_context_marks_failed(tmp_path):
    with pytest.raises(RuntimeError):
        with start_run(tmp_path, "exp") as run:
            run.log_metrics({"x": 1.0})
            raise RuntimeError("boom")
    meta = json.loads((run.path / "meta.json").read_text())
    assert meta["status"] == "FAILED"


def test_artifact_logging(tmp_path):
    src = tmp_path / "model.txt"
    src.write_text("weights")
    store = RunStore(tmp_path / "store", "exp")
    store.log_artifact(src)
    store.log_text("hello", "notes.md")
    assert (store.path / "artifacts" / "model.txt").read_text() == "weights"
    assert (store.path / "artifacts" / "notes.md").read_text() == "hello"

import json

import pytest

from dss_ml_at_scale_tpu.tracking import RunStore, start_run


def test_run_store_roundtrip(tmp_path):
    store = RunStore(tmp_path, "exp1", run_name="my-run")
    store.log_params({"lr": 1e-5, "batch": 212, "obj": {"a": 1}})
    store.log_metrics({"loss": 2.5}, step=1)
    store.log_metrics({"loss": 1.5, "acc": 0.7}, step=2)
    store.finish()

    assert store.params()["lr"] == 1e-5
    ms = store.metrics()
    assert [m["value"] for m in ms if m["name"] == "loss"] == [2.5, 1.5]
    meta = json.loads((store.path / "meta.json").read_text())
    assert meta["status"] == "FINISHED"
    assert meta["run_name"] == "my-run"


def test_log_metrics_after_finish_is_a_noop(tmp_path):
    """A fit thread logging while shutdown races finish() must drop the
    lines, not die on a closed metrics handle — the write path checks
    _closed under the same lock finish() flips it under."""
    store = RunStore(tmp_path, "exp1", run_name="late-logger")
    store.log_metrics({"loss": 2.5}, step=1)
    store.finish()
    store.log_metrics({"loss": 1.5}, step=2)  # must not raise
    assert [m["step"] for m in store.metrics()] == [1]


def test_start_run_context_marks_failed(tmp_path):
    with pytest.raises(RuntimeError):
        with start_run(tmp_path, "exp") as run:
            run.log_metrics({"x": 1.0})
            raise RuntimeError("boom")
    meta = json.loads((run.path / "meta.json").read_text())
    assert meta["status"] == "FAILED"


def test_artifact_logging(tmp_path):
    src = tmp_path / "model.txt"
    src.write_text("weights")
    store = RunStore(tmp_path / "store", "exp")
    store.log_artifact(src)
    store.log_text("hello", "notes.md")
    assert (store.path / "artifacts" / "model.txt").read_text() == "weights"
    assert (store.path / "artifacts" / "notes.md").read_text() == "hello"


def test_list_and_load_runs(tmp_path):
    """The store's read side: list newest-first with wall_seconds,
    load_run returns params + last metric values; foreign junk dirs are
    skipped."""
    import time as _time

    from dss_ml_at_scale_tpu.tracking import (
        RunStore,
        list_runs,
        load_run,
    )

    a = RunStore(tmp_path, "exp1", run_name="first")
    a.log_params({"lr": 0.1})
    a.log_metrics({"loss": 2.0}, step=1)
    a.log_metrics({"loss": 1.0}, step=2)
    a.finish()
    _time.sleep(0.01)
    b = RunStore(tmp_path, "exp2", run_name="second")
    b.finish("FAILED")
    # Junk that must not break listing.
    (tmp_path / "exp1" / "not-a-run").mkdir()
    (tmp_path / "stray.txt").write_text("x")

    runs = list_runs(tmp_path)
    assert [r["run_name"] for r in runs] == ["second", "first"]
    assert runs[1]["wall_seconds"] >= 0
    assert [r["status"] for r in runs] == ["FAILED", "FINISHED"]
    only = list_runs(tmp_path, "exp1")
    assert len(only) == 1 and only[0]["run_id"] == a.run_id

    rec = load_run(tmp_path, "exp1", a.run_id)
    assert rec["params"] == {"lr": 0.1}
    assert rec["last_metrics"]["loss"] == {"value": 1.0, "step": 2}
    assert rec["metric_points"] == 2


def test_runs_cli(tmp_path, capsys, monkeypatch):
    import json as _json

    from dss_ml_at_scale_tpu.config.cli import main
    from dss_ml_at_scale_tpu.tracking import RunStore

    r = RunStore(tmp_path, "imagenet", run_name="t")
    r.log_metrics({"val_acc": 0.9}, step=3)
    r.finish()

    assert main(["runs", "list", "--tracking-root", str(tmp_path)]) == 0
    lines = capsys.readouterr().out.strip().splitlines()
    metas = [_json.loads(l) for l in lines]
    assert metas[0]["run_id"] == r.run_id

    assert main([
        "runs", "show", f"imagenet/{r.run_id}", "--tracking-root", str(tmp_path),
    ]) == 0
    rec = _json.loads(capsys.readouterr().out)
    assert rec["last_metrics"]["val_acc"]["value"] == 0.9

    assert main([
        "runs", "show", "imagenet/nope", "--tracking-root", str(tmp_path),
    ]) == 1
    capsys.readouterr()
    assert main(["runs", "show", "malformed",
                 "--tracking-root", str(tmp_path)]) == 1
    capsys.readouterr()
    # A truncated meta.json (killed writer) gets the diagnosis, not a
    # traceback.
    bad = tmp_path / "imagenet" / "deadbeef0000"
    bad.mkdir()
    (bad / "meta.json").write_text("{trunc")
    assert main(["runs", "show", "imagenet/deadbeef0000",
                 "--tracking-root", str(tmp_path)]) == 1

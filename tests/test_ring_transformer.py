"""Ring attention (8-device simulated mesh) and the Transformer family."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dss_ml_at_scale_tpu.models import TransformerLM, next_token_loss
from dss_ml_at_scale_tpu.ops import attention_reference
from dss_ml_at_scale_tpu.parallel import ring_attention


@pytest.fixture(scope="module")
def seq_mesh():
    return Mesh(np.array(jax.devices()).reshape(8), ("sp",))


def _qkv(rng, b=1, h=2, s=256, d=32, dtype=jnp.float32):
    def mk():
        return jnp.asarray(rng.normal(size=(b, h, s, d)), dtype)

    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_full_attention(rng, seq_mesh, causal):
    q, k, v = _qkv(rng)
    out = jax.jit(
        lambda q, k, v: ring_attention(
            q, k, v, mesh=seq_mesh, axis_name="sp", causal=causal
        )
    )(q, k, v)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_ring_gradients_ride_the_ring(rng, seq_mesh):
    # Reverse-mode through scan + ppermute: must equal full-attention grads.
    q, k, v = _qkv(rng, s=64, d=16)

    def loss_ring(q, k, v):
        out = ring_attention(q, k, v, mesh=seq_mesh, axis_name="sp", causal=True)
        return jnp.sum(out**2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4)


def test_ring_with_sharded_inputs(rng, seq_mesh):
    # Inputs physically sharded over the seq axis: no resharding inserted.
    q, k, v = _qkv(rng, s=512)
    shard = NamedSharding(seq_mesh, P(None, None, "sp", None))
    q, k, v = (jax.device_put(t, shard) for t in (q, k, v))
    out = jax.jit(
        lambda q, k, v: ring_attention(
            q, k, v, mesh=seq_mesh, axis_name="sp", causal=True
        )
    )(q, k, v)
    assert out.sharding.spec == P(None, None, "sp", None)
    np.testing.assert_allclose(
        out, attention_reference(q, k, v, causal=True), atol=2e-5, rtol=2e-5
    )


def test_ring_rejects_indivisible_seq(rng, seq_mesh):
    q, k, v = _qkv(rng, s=100)
    with pytest.raises(ValueError, match="divisible"):
        ring_attention(q, k, v, mesh=seq_mesh, axis_name="sp")


def test_transformer_forward_and_loss(rng):
    model = TransformerLM(
        vocab_size=128, dim=64, num_heads=4, num_layers=2, max_seq=64,
        dtype=jnp.float32, attention="reference",
    )
    tokens = jnp.asarray(rng.integers(0, 128, (2, 64)), jnp.int32)
    params = model.init(jax.random.key(0), tokens)
    logits = model.apply(params, tokens)
    assert logits.shape == (2, 64, 128)
    assert logits.dtype == jnp.float32
    loss = next_token_loss(logits, tokens)
    # Untrained: loss near ln(vocab).
    assert abs(float(loss) - np.log(128)) < 1.0


def test_transformer_flash_matches_reference_attention(rng):
    kw = dict(
        vocab_size=64, dim=64, num_heads=2, num_layers=2, max_seq=128,
        dtype=jnp.float32,
    )
    tokens = jnp.asarray(rng.integers(0, 64, (2, 128)), jnp.int32)
    m_flash = TransformerLM(attention="flash", **kw)
    m_ref = TransformerLM(attention="reference", **kw)
    params = m_flash.init(jax.random.key(0), tokens)
    np.testing.assert_allclose(
        m_flash.apply(params, tokens), m_ref.apply(params, tokens),
        atol=5e-4, rtol=5e-4,
    )


@pytest.mark.slow
def test_transformer_ring_sequence_parallel_train_step(rng, seq_mesh):
    # The long-context training shape: batch=1, sequence sharded 8-way,
    # one full train step (fwd+bwd+Adam) jitted over the mesh.
    model = TransformerLM(
        vocab_size=64, dim=64, num_heads=4, num_layers=2, max_seq=512,
        dtype=jnp.float32, attention="ring", mesh=seq_mesh, axis_name="sp",
    )
    tokens = jnp.asarray(rng.integers(0, 64, (1, 512)), jnp.int32)
    tokens = jax.device_put(tokens, NamedSharding(seq_mesh, P(None, "sp")))
    params = model.init(jax.random.key(0), tokens)
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)

    @jax.jit
    def train_step(params, opt_state, tokens):
        def loss_fn(p):
            return next_token_loss(model.apply(p, tokens), tokens)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    params, opt_state, loss = train_step(params, opt_state, tokens)
    assert np.isfinite(float(loss))

    # Parity: same params, same tokens, reference (unsharded) model.
    m_ref = TransformerLM(
        vocab_size=64, dim=64, num_heads=4, num_layers=2, max_seq=512,
        dtype=jnp.float32, attention="reference",
    )
    loss_ref = next_token_loss(m_ref.apply(params, tokens), tokens)
    loss_ring = next_token_loss(model.apply(params, tokens), tokens)
    np.testing.assert_allclose(float(loss_ring), float(loss_ref), atol=1e-4)


def test_lm_sp_trains_under_trainer(devices8, seq_mesh):
    # The claim in LMTask's docstring, proven: sequence-parallel ring
    # attention rides the IDENTICAL Trainer machinery — batches shard the
    # sequence dim via TrainerConfig.batch_specs and the loss falls
    # toward the Markov source's entropy floor.
    from dss_ml_at_scale_tpu.datagen.tokens import (
        TokenStreamConfig,
        entropy_floor,
        token_batches,
    )
    from dss_ml_at_scale_tpu.parallel import LMTask, Trainer, TrainerConfig

    stream = TokenStreamConfig(
        vocab_size=16, batch_size=4, seq_len=64, concentration=0.05, seed=0
    )
    lm = TransformerLM(
        vocab_size=16, dim=32, num_heads=2, num_layers=1, max_seq=64,
        dtype=jnp.float32, attention="ring", mesh=seq_mesh, axis_name="sp",
    )
    task = LMTask(model=lm, tx=optax.adam(1e-2))
    trainer = Trainer(
        TrainerConfig(
            max_epochs=2,
            steps_per_epoch=40,
            limit_val_batches=2,
            log_every_steps=1000,
            batch_specs={"tokens": P(None, "sp")},
        ),
        mesh=seq_mesh,
    )
    result = trainer.fit(
        task,
        token_batches(stream),
        val_data_factory=lambda: token_batches(
            stream, num_batches=2, sample_seed=999
        ),
    )
    assert len(result.history) == 2
    floor = entropy_floor(stream)
    # Training moved val loss decisively below uniform toward the floor.
    assert result.history[-1]["val_loss"] < 0.7 * np.log(16)
    assert result.history[-1]["val_loss"] > floor - 0.05
    # The batch really was sequence-sharded (not replicated): check via a
    # fresh placement through the same path.
    from dss_ml_at_scale_tpu.runtime.mesh import shard_batch_to_mesh

    placed = shard_batch_to_mesh(
        next(token_batches(stream, num_batches=1)), seq_mesh,
        specs={"tokens": P(None, "sp")},
    )
    assert not placed["tokens"].sharding.is_fully_replicated


def test_transformer_lm_bf16_default_path(rng):
    # The TPU-default dtype (bf16 activations, f32 logits) must produce
    # finite logits close to the f32 reference — the MXU-native
    # configuration every accelerator run uses.
    tokens = jnp.asarray(rng.integers(0, 64, (2, 32)), jnp.int32)

    def build(dtype):
        return TransformerLM(
            vocab_size=64, dim=32, num_heads=4, num_layers=2, max_seq=32,
            dtype=dtype, attention="reference",
        )

    lm16, lm32 = build(jnp.bfloat16), build(jnp.float32)
    params = lm32.init(jax.random.key(0), tokens)  # f32 master weights
    out16 = lm16.apply(params, tokens)
    out32 = lm32.apply(params, tokens)
    assert out16.dtype == jnp.float32  # logits always f32
    assert np.isfinite(np.asarray(out16)).all()
    # bf16 has ~3 decimal digits; compare post-softmax where it matters.
    p16 = jax.nn.softmax(out16, axis=-1)
    p32 = jax.nn.softmax(out32, axis=-1)
    assert float(jnp.abs(p16 - p32).max()) < 0.05
    loss16 = float(next_token_loss(out16, tokens))
    loss32 = float(next_token_loss(out32, tokens))
    assert abs(loss16 - loss32) < 0.05 * max(1.0, loss32)


def test_ring_attention_bf16(rng, seq_mesh):
    q, k, v = _qkv(rng, s=256, dtype=jnp.bfloat16)
    out = jax.jit(
        lambda q, k, v: ring_attention(
            q, k, v, mesh=seq_mesh, axis_name="sp", causal=True
        )
    )(q, k, v)
    assert out.dtype == jnp.bfloat16
    ref = attention_reference(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32),
        causal=True,
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), atol=2e-2, rtol=2e-2
    )

"""Tier-1 face of scripts/check_bare_except.py: no bare ``except:`` and
no silent ``except Exception: pass`` outside the audited allowlist —
swallowed errors are how robustness bugs hide."""

import importlib.util
from pathlib import Path


def _load_linter():
    path = (
        Path(__file__).resolve().parents[1] / "scripts"
        / "check_bare_except.py"
    )
    spec = importlib.util.spec_from_file_location("check_bare_except", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_no_swallowed_errors():
    linter = _load_linter()
    violations = linter.find_violations()
    assert violations == [], "\n".join(violations)


def test_linter_flags_synthetic_violations(tmp_path):
    """The lint actually bites: a tree with both banned patterns and a
    justified-but-unlisted silent handler yields exactly those lines."""
    linter = _load_linter()
    pkg = tmp_path / "dss_ml_at_scale_tpu"
    pkg.mkdir()
    (tmp_path / "scripts").mkdir()
    (pkg / "bad.py").write_text(
        "try:\n    x = 1\nexcept:\n    raise\n"
        "try:\n    y = 2\nexcept Exception:\n    pass\n"
        "try:\n    z = 3\nexcept (ValueError, BaseException):\n    pass\n"
        "try:\n    ok = 4\nexcept ValueError:\n    pass\n"  # narrow: fine
        "try:\n    ok2 = 5\nexcept Exception as e:\n    print(e)\n"  # acts
    )
    violations = linter.find_violations(tmp_path)
    assert len(violations) == 3
    assert "bare `except:`" in violations[0]
    assert "silent broad except" in violations[1]
    assert "silent broad except" in violations[2]

"""Tier-1 face of the ``dsst audit`` IR-level program auditor.

Three layers, mirroring ``test_lint.py``:

- **the real gate**: the full rule suite over the LIVE entrypoint
  registry must be clean against the committed ``AUDIT_BASELINE.json``
  (zero active findings, zero stale entries, every accepted entry
  justified) — this is ROADMAP item 1's "partitioned, donated,
  no-hidden-allgather" exit gate, enforced before any TPU exists;
- **per-rule fixtures**: live positive/negative entrypoint twins under
  ``tests/fixtures/audit/`` prove each IR rule bites the violation it
  claims (an un-donated train-step twin, a latent-f64 op, a callback
  in a jit, a surprise all-gather) and spares the clean idiom;
- **framework semantics**: per-entrypoint suppressions (reason
  mandatory), trace failures surfacing as findings, and baseline
  pin / reopen-on-hash-change / reopen-on-cost-regression / expire.

The audit compiles every registry entrypoint on the 8-device CPU mesh
(conftest multiplexes the host platform), so the registry gate is the
most expensive single test in tier-1 — it runs ONCE via the shared
cache below.
"""

from __future__ import annotations

import functools
import importlib.util
import json
from pathlib import Path

import pytest

from dss_ml_at_scale_tpu.analysis.audit import (
    DEFAULT_AUDIT_BASELINE,
    AuditUsageError,
    default_audit_mesh,
    entrypoint_names,
    load_audit_baseline,
    rule_names,
    run_audit,
    write_audit_baseline,
)
from dss_ml_at_scale_tpu.config.cli import main

FIXTURES = Path(__file__).resolve().parent / "fixtures" / "audit"

# A path that never exists: run_audit sees an empty baseline.
NO_BASELINE = FIXTURES / "_never_written.json"


@functools.lru_cache(maxsize=8)
def _fixture(name: str):
    spec = importlib.util.spec_from_file_location(
        f"_audit_fixture_{name}", FIXTURES / f"{name}_fixture.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@functools.lru_cache(maxsize=1)
def _mesh():
    return default_audit_mesh()


def _audit(builders: dict, rules: list[str], baseline=NO_BASELINE):
    return run_audit(
        specs=builders, rules=rules, baseline_path=baseline, mesh=_mesh()
    )


# -- the real gate: the live registry is clean against the baseline ----------


@functools.lru_cache(maxsize=1)
def _registry_result():
    """ONE full-registry audit shared by every gate below — each
    entrypoint traces/lowers/compiles exactly once per tier-1 run."""
    return run_audit()


def test_registry_clean_against_committed_baseline():
    res = _registry_result()
    assert res.findings == [], "\n".join(f.text() for f in res.findings)
    assert res.stale_baseline == [], (
        "stale audit baseline entries (programs or accepted findings "
        "no longer produced): "
        + ", ".join(e["key"] for e in res.stale_baseline)
    )
    assert res.exit_code == 0


def test_registry_covers_the_contracted_entrypoints():
    """The ROADMAP-item-1 contract surface: losing one of these from
    the registry silently un-audits a production program."""
    expected = {
        "train_step.classifier",
        "train_step.classifier.health",
        "eval_step.classifier",
        "train_step.lm",
        "train_step.pipelined_lm",
        "decode_step.lm",
        "serving.score",
        "ops.fused_matmul.grad",
        "ops.fused_norm.grad",
        "ops.flash_attention.grad",
        "sarimax.batched_fit",
    }
    assert expected <= set(entrypoint_names())
    assert expected <= set(_registry_result().programs)


def test_every_audit_baseline_entry_has_a_reason():
    baseline = load_audit_baseline(DEFAULT_AUDIT_BASELINE)
    assert baseline["programs"], "committed audit baseline pins nothing"
    for key, entry in baseline["entries"].items():
        assert str(entry.get("reason", "")).strip(), (
            f"audit baseline entry {key} has no reason"
        )


def test_audit_emits_registered_telemetry():
    from dss_ml_at_scale_tpu import telemetry

    def val(name: str) -> float:
        for m in telemetry.snapshot()["metrics"]:
            if m["name"] == name and not m["labels"]:
                return m["value"]
        return 0.0

    before = val("audit_entrypoints_total")
    _registry_result()  # cached: inc'd once, on whichever test ran first
    assert val("audit_entrypoints_total") >= before
    assert val("audit_entrypoints_total") >= len(entrypoint_names())


# -- per-rule fixtures --------------------------------------------------------


def test_donation_flags_undonated_twin():
    fx = _fixture("donation")
    res = _audit({"fixture.donation.pos": fx.build_positive}, ["donation"])
    assert [f.rule for f in res.findings] == ["donation"], [
        f.text() for f in res.findings
    ]
    assert res.findings[0].ident == "arg0.leaf0"
    assert res.exit_code == 1


def test_donation_spares_donated_twin():
    fx = _fixture("donation")
    res = _audit({"fixture.donation.neg": fx.build_negative}, ["donation"])
    assert res.findings == [], [f.text() for f in res.findings]


def test_dtype_flags_latent_f64():
    fx = _fixture("dtype")
    res = _audit(
        {"fixture.dtype.wide.pos": fx.build_positive_wide},
        ["dtype-discipline"],
    )
    assert res.findings, "latent f64 promotion not flagged"
    assert all(f.ident.startswith("wide:") for f in res.findings), [
        f.text() for f in res.findings
    ]


def test_dtype_flags_weak_type_churn():
    fx = _fixture("dtype")
    res = _audit(
        {"fixture.dtype.churn.pos": fx.build_positive_churn},
        ["dtype-discipline"],
    )
    assert [f.ident for f in res.findings] == ["weak-churn"], [
        f.text() for f in res.findings
    ]


def test_dtype_spares_pinned_twin():
    fx = _fixture("dtype")
    res = _audit(
        {"fixture.dtype.neg": fx.build_negative}, ["dtype-discipline"]
    )
    assert res.findings == [], [f.text() for f in res.findings]


def test_host_interop_flags_callback_in_jit():
    fx = _fixture("host_interop")
    res = _audit(
        {"fixture.host_interop.pos": fx.build_positive}, ["host-interop"]
    )
    assert [f.ident for f in res.findings] == [
        "callback:debug_callback"
    ], [f.text() for f in res.findings]


def test_host_interop_spares_declared_coldpath():
    fx = _fixture("host_interop")
    res = _audit(
        {"fixture.host_interop.neg": fx.build_negative}, ["host-interop"]
    )
    assert res.findings == []


def test_sharding_flags_surprise_allgather():
    fx = _fixture("sharding")
    res = _audit(
        {"fixture.sharding.gather.pos": fx.build_positive_gather},
        ["sharding-collectives"],
    )
    idents = [f.ident for f in res.findings]
    assert any(i.startswith("all-gather:") for i in idents), [
        f.text() for f in res.findings
    ]


def test_sharding_flags_oversized_replicated_input():
    fx = _fixture("sharding")
    res = _audit(
        {"fixture.sharding.replicated.pos": fx.build_positive_replicated},
        ["sharding-collectives"],
    )
    assert [f.ident for f in res.findings] == ["replicated:arg0.leaf0"], [
        f.text() for f in res.findings
    ]


def test_sharding_sums_tuple_shaped_combined_collectives():
    """XLA's collective combiner and async `-start` ops emit
    TUPLE-shaped collectives — exactly the largest ones. The rule must
    sum every tuple element (here 64 MiB + 32 MiB, each alone at or
    under the 64 MiB all-reduce ceiling) and must not double-count the
    `-done` half of an async pair."""
    from dss_ml_at_scale_tpu.analysis.audit.rules import (
        ShardingCollectivesRule,
    )

    class _Spec:
        collective_limits = None
        replicated_bytes_limit = None

    class _Ctx:
        spec = _Spec()
        name = "fixture.tuple_collective"
        optimized_hlo = (
            "  %all-reduce.1 = (f32[16777216]{0}, f32[8388608]{0})"
            " all-reduce(f32[16777216]{0} %a, f32[8388608]{0} %b),"
            " replica_groups={}\n"
            "  %ag-start = (f32[262144]{0}, f32[2097152]{0})"
            " all-gather-start(f32[262144]{0} %c), dimensions={0}\n"
            "  %ag-done = f32[2097152]{0}"
            " all-gather-done((f32[262144]{0}, f32[2097152]{0})"
            " %ag-start)\n"
        )

        def flat_avals(self):
            return []

    findings = list(ShardingCollectivesRule().check(_Ctx()))
    by_op = {f.ident.split(":")[0]: f for f in findings}
    assert set(by_op) == {"all-reduce", "all-gather"}, [
        f.text() for f in findings
    ]
    assert "100663296 bytes" in by_op["all-reduce"].message
    # ONE all-gather finding: the -start counted, the -done skipped.
    assert sum(1 for f in findings if f.ident.startswith("all-gather")) == 1


def test_sharding_spares_sharded_elementwise():
    fx = _fixture("sharding")
    res = _audit(
        {"fixture.sharding.neg": fx.build_negative},
        ["sharding-collectives"],
    )
    assert res.findings == [], [f.text() for f in res.findings]


# -- framework: suppressions and trace failures -------------------------------


def test_suppression_with_reason_silences_and_is_reported():
    fx = _fixture("host_interop")
    res = _audit(
        {"fixture.host_interop.suppressed": fx.build_suppressed},
        ["host-interop"],
    )
    assert res.findings == []
    assert len(res.suppressed) == 1
    assert res.exit_code == 0


def test_suppression_without_reason_is_a_usage_error():
    from dss_ml_at_scale_tpu.analysis.audit import ProgramSpec

    def build(mesh):
        import jax.numpy as jnp

        return ProgramSpec(
            name="fixture.bad_suppress",
            fn=lambda x: x,
            args=(jnp.zeros((4,), jnp.float32),),
            suppress={"host-interop": "  "},
        )

    with pytest.raises(AuditUsageError):
        _audit({"fixture.bad_suppress": build}, ["host-interop"])


def test_builder_failure_is_a_trace_error_finding():
    def build(mesh):
        raise ValueError("fixture builder exploded")

    res = _audit({"fixture.broken_builder": build}, ["host-interop"])
    assert [(f.rule, f.ident) for f in res.findings] == [
        ("trace-error", "build")
    ]
    assert res.exit_code == 1


def test_untraceable_fn_is_a_trace_error_finding():
    from dss_ml_at_scale_tpu.analysis.audit import ProgramSpec

    def build(mesh):
        import jax.numpy as jnp

        def f(x):
            if x.sum() > 0:  # concretization error under tracing
                return x
            return -x

        return ProgramSpec(
            name="fixture.untraceable", fn=f,
            args=(jnp.zeros((4,), jnp.float32),),
        )

    res = _audit({"fixture.untraceable": build}, ["host-interop"])
    assert res.findings and all(
        f.rule == "trace-error" for f in res.findings
    ), [f.text() for f in res.findings]


def test_unknown_entrypoint_and_rule_are_usage_errors():
    with pytest.raises(AuditUsageError):
        run_audit(["no.such.entrypoint"], mesh=_mesh())
    with pytest.raises(AuditUsageError):
        run_audit(rules=["no-such-rule"], mesh=_mesh())


# -- baseline: pin, reopen on hash change, reopen on cost regression ---------


def test_program_baseline_pin_and_reopen(tmp_path):
    fx = _fixture("baseline")
    bl = tmp_path / "audit_baseline.json"
    name = fx.NAME
    rules = rule_names()

    # 1. Unpinned program: the rule demands a baseline.
    res = _audit({name: fx.build_v1}, rules, baseline=bl)
    assert [f.ident for f in res.findings] == ["unbaselined"]

    # 2. Pin it; the same program is now clean.
    write_audit_baseline(bl, res, load_audit_baseline(bl), None)
    res2 = _audit({name: fx.build_v1}, rules, baseline=bl)
    assert res2.findings == [], [f.text() for f in res2.findings]
    assert res2.exit_code == 0

    # 3. A semantic edit under the same name reopens on the hash (the
    # extra add also moves the byte cost, which may reopen too — the
    # hash is the guaranteed signal).
    res3 = _audit({name: fx.build_v2}, rules, baseline=bl)
    idents = [f.ident for f in res3.findings]
    assert "hash" in idents, [f.text() for f in res3.findings]
    assert all(f.rule == "program-baseline" for f in res3.findings)
    assert res3.exit_code == 1


def test_program_baseline_reopens_on_cost_regression(tmp_path):
    fx = _fixture("baseline")
    bl = tmp_path / "audit_baseline.json"
    name = fx.NAME
    rules = rule_names()

    res = _audit({name: fx.build_v1}, rules, baseline=bl)
    write_audit_baseline(bl, res, load_audit_baseline(bl), None)
    flops = res.programs[name]["flops"]
    if flops is None or flops <= 0:
        pytest.skip("backend cost model reports no flops on this host")

    # Shrink the committed budget below measured cost: same program,
    # now over budget — the regression arm must fire.
    data = json.loads(bl.read_text())
    data["programs"][name]["flops"] = flops / 2.0
    bl.write_text(json.dumps(data))
    res2 = _audit({name: fx.build_v1}, rules, baseline=bl)
    assert [f.ident for f in res2.findings] == ["flops"], [
        f.text() for f in res2.findings
    ]


def test_accepted_finding_expires_when_fixed(tmp_path):
    """A baselined finding whose program got fixed is stale ballast and
    FAILS the audit until the baseline is regenerated."""
    fx = _fixture("host_interop")
    bl = tmp_path / "audit_baseline.json"

    def dirty(mesh):
        spec = fx.build_positive(mesh)
        import dataclasses

        return dataclasses.replace(spec, name="fixture.hi")

    def clean(mesh):
        spec = fx.build_negative(mesh)
        import dataclasses

        return dataclasses.replace(spec, name="fixture.hi")

    res = _audit({"fixture.hi": dirty}, ["host-interop"], baseline=bl)
    assert len(res.findings) == 1
    write_audit_baseline(
        bl, res, load_audit_baseline(bl), "accepted for the fixture"
    )
    res2 = _audit({"fixture.hi": dirty}, ["host-interop"], baseline=bl)
    assert res2.findings == [] and len(res2.baselined) == 1

    res3 = _audit({"fixture.hi": clean}, ["host-interop"], baseline=bl)
    assert res3.findings == []
    assert len(res3.stale_baseline) == 1
    assert res3.exit_code == 1


def test_update_baseline_refuses_a_broken_registry(tmp_path):
    """A trace-errored entrypoint has no program record this run — a
    rewrite would silently drop its committed pin, and the fixed-up
    entrypoint would later re-pin fresh, defeating drift detection."""
    bl = tmp_path / "audit_baseline.json"
    bl.write_text(json.dumps({
        "entries": {},
        "programs": {"fixture.broken_builder": {"hash": "cafe",
                                                "flops": 1, "bytes": 1}},
    }))

    def build(mesh):
        raise ValueError("fixture builder exploded")

    res = _audit({"fixture.broken_builder": build}, ["host-interop"],
                 baseline=bl)
    before = bl.read_text()
    with pytest.raises(AuditUsageError, match="trace errors"):
        write_audit_baseline(bl, res, load_audit_baseline(bl), "r")
    assert bl.read_text() == before  # pin survives untouched


def test_new_baseline_entry_requires_reason(tmp_path):
    fx = _fixture("host_interop")
    bl = tmp_path / "audit_baseline.json"
    res = _audit(
        {"fixture.host_interop.pos": fx.build_positive},
        ["host-interop"], baseline=bl,
    )
    assert res.findings
    with pytest.raises(AuditUsageError):
        write_audit_baseline(bl, res, load_audit_baseline(bl), None)


# -- CLI ----------------------------------------------------------------------


def test_cli_list_rules(capsys):
    assert main(["audit", "--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in (
        "donation", "dtype-discipline", "sharding-collectives",
        "host-interop", "program-baseline",
    ):
        assert rule in out


def test_cli_list_entrypoints(capsys):
    assert main(["audit", "--list-entrypoints"]) == 0
    out = capsys.readouterr().out
    assert "train_step.classifier" in out
    assert "sarimax.batched_fit" in out


def test_cli_unknown_entrypoint_exits_2():
    assert main(["audit", "--entrypoints", "no.such.ep"]) == 2


def test_cli_update_baseline_rejects_subset_runs():
    """Mirror of `lint --changed --update-baseline`: a subset run must
    never rewrite the whole-registry baseline (it would drop every pin
    it didn't re-check). Guarded BEFORE tracing, so this is cheap."""
    for subset in (["--entrypoints", "ops.fused_norm.grad"],
                   ["--rules", "donation"]):
        assert main([
            "audit", *subset, "--update-baseline", "--reason", "nope",
        ]) == 2


def test_cli_single_entrypoint_json(capsys):
    rc = main([
        "audit", "--entrypoints", "ops.fused_norm.grad", "--json",
    ])
    out = capsys.readouterr().out
    assert rc == 0, out
    payload = json.loads(out)
    assert payload["ok"] is True
    assert payload["entrypoints"] == ["ops.fused_norm.grad"]
    assert "ops.fused_norm.grad" in payload["programs"]
    assert set(payload["counts"]) == {
        "active", "baselined", "suppressed", "stale_baseline"
    }

import pytest
import jax
import jax.numpy as jnp
import numpy as np

from dss_ml_at_scale_tpu.models import ResNet, ResNet50
from dss_ml_at_scale_tpu.models.resnet import ResNetBlock


def tiny_resnet(num_classes=10):
    return ResNet(
        stage_sizes=[1, 1],
        block_cls=ResNetBlock,
        num_classes=num_classes,
        num_filters=8,
        dtype=jnp.float32,
    )


def test_tiny_resnet_forward_shapes():
    model = tiny_resnet()
    x = jnp.ones((2, 32, 32, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32
    assert "batch_stats" in variables


def test_train_mode_updates_batch_stats():
    model = tiny_resnet()
    x = jnp.asarray(np.random.default_rng(0).normal(size=(4, 32, 32, 3)), jnp.float32)
    variables = model.init(jax.random.key(0), x, train=False)
    _, updates = model.apply(variables, x, train=True, mutable=["batch_stats"])
    leaves_before = jax.tree_util.tree_leaves(variables["batch_stats"])
    leaves_after = jax.tree_util.tree_leaves(updates["batch_stats"])
    assert any(
        not np.allclose(a, b) for a, b in zip(leaves_before, leaves_after)
    )


def test_resnet50_param_count():
    """ResNet-50 must match the canonical ~25.6M parameters."""
    model = ResNet50(num_classes=1000)
    variables = jax.eval_shape(
        lambda: model.init(jax.random.key(0), jnp.ones((1, 224, 224, 3)), train=False)
    )
    n = sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(variables["params"]))
    assert abs(n - 25_557_032) / 25_557_032 < 0.01, n


def test_topk_accuracy():
    from dss_ml_at_scale_tpu.models import topk_accuracy

    logits = jnp.asarray([
        [9.0, 5.0, 1.0, 0.0],   # top-2 = {0, 1}
        [0.0, 1.0, 5.0, 9.0],   # top-2 = {3, 2}
        [1.0, 9.0, 5.0, 0.0],   # top-2 = {1, 2}
    ])
    labels = jnp.asarray([1, 0, 0])
    # top-1: none right; top-2: rows 0 (label 1 in {0,1}); top-4: all.
    assert float(topk_accuracy(logits, labels, 1)) == 0.0
    assert float(topk_accuracy(logits, labels, 2)) == pytest.approx(1 / 3)
    assert float(topk_accuracy(logits, labels, 4)) == 1.0
    with pytest.raises(ValueError, match="at least 9 classes"):
        topk_accuracy(logits, labels, 9)


def test_eval_topk_in_trainer(devices8):
    import optax

    from test_trainer import synthetic_batches

    from dss_ml_at_scale_tpu.parallel import (
        ClassifierTask,
        Trainer,
        TrainerConfig,
    )
    from dss_ml_at_scale_tpu.runtime import make_mesh

    task = ClassifierTask(model=tiny_resnet(num_classes=4),
                          tx=optax.adam(1e-2), eval_topk=(2,))
    trainer = Trainer(
        TrainerConfig(max_epochs=1, steps_per_epoch=5, log_every_steps=1000),
        mesh=make_mesh(),
    )
    result = trainer.fit(
        task, iter(synthetic_batches(5)),
        val_data_factory=lambda: synthetic_batches(2, seed=3),
    )
    h = result.history[-1]
    assert "val_top2_acc" in h
    assert h["val_top2_acc"] >= h["val_acc"]

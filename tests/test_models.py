import jax
import jax.numpy as jnp
import numpy as np

from dss_ml_at_scale_tpu.models import ResNet, ResNet50
from dss_ml_at_scale_tpu.models.resnet import ResNetBlock


def tiny_resnet(num_classes=10):
    return ResNet(
        stage_sizes=[1, 1],
        block_cls=ResNetBlock,
        num_classes=num_classes,
        num_filters=8,
        dtype=jnp.float32,
    )


def test_tiny_resnet_forward_shapes():
    model = tiny_resnet()
    x = jnp.ones((2, 32, 32, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32
    assert "batch_stats" in variables


def test_train_mode_updates_batch_stats():
    model = tiny_resnet()
    x = jnp.asarray(np.random.default_rng(0).normal(size=(4, 32, 32, 3)), jnp.float32)
    variables = model.init(jax.random.key(0), x, train=False)
    _, updates = model.apply(variables, x, train=True, mutable=["batch_stats"])
    leaves_before = jax.tree_util.tree_leaves(variables["batch_stats"])
    leaves_after = jax.tree_util.tree_leaves(updates["batch_stats"])
    assert any(
        not np.allclose(a, b) for a, b in zip(leaves_before, leaves_after)
    )


def test_resnet50_param_count():
    """ResNet-50 must match the canonical ~25.6M parameters."""
    model = ResNet50(num_classes=1000)
    variables = jax.eval_shape(
        lambda: model.init(jax.random.key(0), jnp.ones((1, 224, 224, 3)), train=False)
    )
    n = sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(variables["params"]))
    assert abs(n - 25_557_032) / 25_557_032 < 0.01, n

"""Tier-1 face of the ``dsst lint`` static-analysis subsystem.

Three layers:

- **the real gate**: the full 8-rule suite over the shipped package must
  be clean against the committed baseline (zero unbaselined findings,
  zero stale entries, every baseline entry justified);
- **per-rule fixtures**: positive/negative snippets under
  ``tests/fixtures/lint/`` prove each checker bites what it claims and
  spares the idioms it must spare;
- **framework semantics**: suppression parsing (reason mandatory) and
  baseline add/expire.

``test_no_print.py`` / ``test_no_bare_except.py`` / ``test_fault_sites.py``
are one-line imports of the per-rule gates here, so external references
to those files keep working.
"""

from __future__ import annotations

import functools
from pathlib import Path

import pytest

from dss_ml_at_scale_tpu.analysis import (
    LintUsageError,
    lint_text,
    load_baseline,
    run_lint,
    write_baseline,
)
from dss_ml_at_scale_tpu.analysis.checkers.bare_except import (
    BareExceptChecker,
)
from dss_ml_at_scale_tpu.analysis.checkers.bench_registry import (
    BenchRegistryChecker,
)
from dss_ml_at_scale_tpu.analysis.checkers.durable_write import (
    DurableWriteChecker,
)
from dss_ml_at_scale_tpu.analysis.checkers.fault_sites import (
    FaultSitesChecker,
)
from dss_ml_at_scale_tpu.analysis.checkers.host_sync import HostSyncChecker
from dss_ml_at_scale_tpu.analysis.checkers.lock_discipline import (
    LockDisciplineChecker,
)
from dss_ml_at_scale_tpu.analysis.checkers.no_print import NoPrintChecker
from dss_ml_at_scale_tpu.analysis.checkers.retrace_hazard import (
    RetraceHazardChecker,
)
from dss_ml_at_scale_tpu.analysis.checkers.slo_registry import (
    SloRegistryChecker,
)
from dss_ml_at_scale_tpu.analysis.checkers.span_discipline import (
    SpanDisciplineChecker,
)
from dss_ml_at_scale_tpu.analysis.checkers.telemetry_registry import (
    TelemetryRegistryChecker,
)
from dss_ml_at_scale_tpu.analysis.checkers.trace_safety import (
    TraceSafetyChecker,
)

FIXTURES = Path(__file__).resolve().parent / "fixtures" / "lint"


# -- the real gate: the shipped package is lint-clean -------------------------


@functools.lru_cache(maxsize=1)
def _full_result():
    """ONE whole-package scan shared by the full gate and the per-rule
    gates (including their re-imports from the migrated test files) —
    the package is parsed once per tier-1 run, not seven times."""
    return run_lint()


def test_full_suite_clean_against_baseline():
    res = _full_result()
    assert res.findings == [], "\n".join(f.text() for f in res.findings)
    assert res.stale_baseline == [], (
        "stale baseline entries (findings fixed but ballast kept): "
        + ", ".join(e["key"] for e in res.stale_baseline)
    )


def test_every_baseline_entry_has_a_reason():
    from dss_ml_at_scale_tpu.analysis import DEFAULT_BASELINE

    entries = load_baseline(DEFAULT_BASELINE)
    for key, entry in entries.items():
        assert str(entry.get("reason", "")).strip(), (
            f"baseline entry {key} has no reason"
        )


def _rule_clean(rule: str):
    bad = [f for f in _full_result().findings if f.rule == rule]
    assert bad == [], "\n".join(f.text() for f in bad)


def test_no_print_clean():
    _rule_clean("no-print")


def test_no_bare_except_clean():
    _rule_clean("bare-except")


def test_fault_sites_clean():
    _rule_clean("fault-sites")


def test_durable_write_clean():
    _rule_clean("durable-write")


def test_span_discipline_clean():
    _rule_clean("span-discipline")


# -- per-rule fixtures --------------------------------------------------------

# rule -> (checker factory, expected positive finding count)
RULES = {
    "no_print": (lambda: NoPrintChecker(), 2),
    "bare_except": (lambda: BareExceptChecker(), 3),
    "durable_write": (lambda: DurableWriteChecker(), 6),
    "fault_sites_pos": (
        lambda: FaultSitesChecker(known={"reader.next": "doc"}), 3,
    ),
    "fault_sites_neg": (
        lambda: FaultSitesChecker(known={"rpc.send": "transport"}), None,
    ),
    "trace_safety": (lambda: TraceSafetyChecker(), 5),
    "retrace_hazard": (lambda: RetraceHazardChecker(), 5),
    "host_sync": (lambda: HostSyncChecker(), 5),
    "lock_discipline": (lambda: LockDisciplineChecker(), 7),
    "telemetry_registry_pos": (
        lambda: TelemetryRegistryChecker(
            known={"requests_total": "counter", "dead_gauge": "gauge"}
        ), 4,
    ),
    "telemetry_registry_neg": (
        lambda: TelemetryRegistryChecker(
            known={"requests_total": "counter", "depth": "gauge"}
        ), None,
    ),
    "span_discipline_pos": (
        lambda: SpanDisciplineChecker(
            known={"train_step": "", "dead.span": ""}
        ), 4,
    ),
    "span_discipline_neg": (
        lambda: SpanDisciplineChecker(
            known={"train_step": "", "train_epoch": ""}
        ), None,
    ),
    "bench_registry_pos": (
        lambda: BenchRegistryChecker(known={
            "decode": ("decode_images_per_sec",),
            "gated": ("a_metric", "b_metric"),
            "dead_scenario": ("x",),
        }), 6,
    ),
    "bench_registry_neg": (
        lambda: BenchRegistryChecker(known={
            "decode": ("decode_images_per_sec",),
            "kwform": ("a_metric",),
        }), None,
    ),
    "slo_registry_pos": (
        lambda: SloRegistryChecker(known={
            "serving_latency_p99": "latency", "ttft_p99": "first token",
            "dead_slo": "unmeasured",
        }), 5,
    ),
    "slo_registry_neg": (
        lambda: SloRegistryChecker(known={
            "serving_latency_p99": "latency", "ttft_p99": "first token",
            "inter_token_p99": "token gap",
        }), None,
    ),
}


def _fixture(name: str) -> str:
    return (FIXTURES / f"{name}.py").read_text(encoding="utf-8")


@pytest.mark.parametrize(
    "rule", [r for r, (_, n) in RULES.items() if n is not None]
)
def test_rule_flags_positive_fixture(rule):
    factory, expected = RULES[rule]
    base = rule.removesuffix("_pos")
    findings = lint_text(factory(), _fixture(f"{base}_positive"))
    texts = "\n".join(f.text() for f in findings)
    assert len(findings) == expected, (
        f"expected {expected} findings, got {len(findings)}:\n{texts}"
    )


@pytest.mark.parametrize(
    "rule", [r for r in RULES if not r.endswith("_pos")]
)
def test_rule_spares_negative_fixture(rule):
    factory, _ = RULES[rule]
    base = rule.removesuffix("_pos").removesuffix("_neg")
    findings = lint_text(factory(), _fixture(f"{base}_negative"))
    assert findings == [], "\n".join(f.text() for f in findings)


# -- suppression semantics ----------------------------------------------------


def test_suppression_silences_with_reason():
    src = (
        "def f(x):\n"
        "    print(x)  # dsst: ignore[no-print] CLI-adjacent debug shim\n"
    )
    assert lint_text(NoPrintChecker(), src) == []


def test_suppression_on_line_above():
    src = (
        "def f(x):\n"
        "    # dsst: ignore[no-print] annotates the next line\n"
        "    print(x)\n"
    )
    assert lint_text(NoPrintChecker(), src) == []


def test_suppression_wrong_rule_does_not_silence():
    src = (
        "def f(x):\n"
        "    print(x)  # dsst: ignore[bare-except] wrong rule named\n"
    )
    findings = lint_text(NoPrintChecker(), src)
    assert len(findings) == 1 and findings[0].rule == "no-print"


def test_suppression_without_reason_is_a_finding(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        "def f(x):\n"
        "    print(x)  # dsst: ignore[no-print]\n"
    )
    res = run_lint(
        ["no-print"],
        roots=[("package", pkg)],
        baseline_path=tmp_path / "baseline.json",
    )
    rules = sorted(f.rule for f in res.findings)
    # The reasonless comment does NOT suppress, and is itself flagged.
    assert rules == ["no-print", "suppression"], [
        f.text() for f in res.findings
    ]


# -- baseline add / expire semantics ------------------------------------------


def _write_violating_pkg(tmp_path: Path) -> Path:
    pkg = tmp_path / "pkg"
    pkg.mkdir(exist_ok=True)
    (pkg / "mod.py").write_text("def f(x):\n    print(x)\n")
    return pkg


def test_baseline_add_then_clean(tmp_path):
    pkg = _write_violating_pkg(tmp_path)
    bl = tmp_path / "baseline.json"
    roots = [("package", pkg)]
    res = run_lint(["no-print"], roots=roots, baseline_path=bl)
    assert len(res.findings) == 1 and res.exit_code == 1
    write_baseline(bl, res.findings, {}, "known debug print, PR pending")
    res2 = run_lint(["no-print"], roots=roots, baseline_path=bl)
    assert res2.findings == [] and res2.exit_code == 0
    assert len(res2.baselined) == 1


def test_baseline_requires_reason_for_new_entries(tmp_path):
    pkg = _write_violating_pkg(tmp_path)
    bl = tmp_path / "baseline.json"
    res = run_lint(["no-print"], roots=[("package", pkg)], baseline_path=bl)
    with pytest.raises(LintUsageError):
        write_baseline(bl, res.findings, {}, None)


def test_baseline_expires_when_finding_fixed(tmp_path):
    pkg = _write_violating_pkg(tmp_path)
    bl = tmp_path / "baseline.json"
    roots = [("package", pkg)]
    res = run_lint(["no-print"], roots=roots, baseline_path=bl)
    write_baseline(bl, res.findings, {}, "pending")
    # Fix the violation: the baseline entry is now stale ballast and
    # must FAIL the run until regenerated.
    (pkg / "mod.py").write_text("def f(x):\n    return x\n")
    res2 = run_lint(["no-print"], roots=roots, baseline_path=bl)
    assert res2.findings == []
    assert len(res2.stale_baseline) == 1
    assert res2.exit_code == 1
    # --update-baseline semantics: rewrite from current findings drops it.
    write_baseline(bl, [], load_baseline(bl), None)
    res3 = run_lint(["no-print"], roots=roots, baseline_path=bl)
    assert res3.exit_code == 0


def test_baseline_reopens_when_flagged_line_edited(tmp_path):
    pkg = _write_violating_pkg(tmp_path)
    bl = tmp_path / "baseline.json"
    roots = [("package", pkg)]
    res = run_lint(["no-print"], roots=roots, baseline_path=bl)
    write_baseline(bl, res.findings, {}, "pending")
    # Edit the flagged line: the content-addressed key changes, so the
    # finding re-opens (and the old entry goes stale).
    (pkg / "mod.py").write_text("def f(x):\n    print(x, x)\n")
    res2 = run_lint(["no-print"], roots=roots, baseline_path=bl)
    assert len(res2.findings) == 1
    assert len(res2.stale_baseline) == 1


def test_unrelated_edits_keep_baseline_match(tmp_path):
    pkg = _write_violating_pkg(tmp_path)
    bl = tmp_path / "baseline.json"
    roots = [("package", pkg)]
    res = run_lint(["no-print"], roots=roots, baseline_path=bl)
    write_baseline(bl, res.findings, {}, "pending")
    # Insert lines ABOVE the finding: line numbers shift but the key
    # (hash of the line text, not its number) still matches.
    (pkg / "mod.py").write_text(
        "import logging\n\n\ndef f(x):\n    print(x)\n"
    )
    res2 = run_lint(["no-print"], roots=roots, baseline_path=bl)
    assert res2.findings == [] and res2.stale_baseline == []


def test_stacked_suppression_comments_merge():
    src = (
        "def f(x):\n"
        "    # dsst: ignore[no-print] tolerated here\n"
        "    # dsst: ignore[bare-except] also tolerated\n"
        "    print(x)\n"
    )
    # The SECOND comment's own line inherits the first's rules too, and
    # the statement line carries both — neither clobbers the other.
    assert lint_text(NoPrintChecker(), src) == []


def test_shim_preserves_config_exemption_and_distinct_paths(tmp_path):
    """scripts/check_no_print.py on a foreign tree: config/ stays
    exempt and same-named files in different dirs stay distinct."""
    import importlib.util

    pkg = tmp_path / "somepkg"
    (pkg / "config").mkdir(parents=True)
    (pkg / "core").mkdir()
    (pkg / "core2").mkdir()
    (pkg / "config" / "cli.py").write_text("print('cli owns stdout')\n")
    (pkg / "core" / "mod.py").write_text("print('a')\n")
    (pkg / "core2" / "mod.py").write_text("print('b')\nprint('c')\n")
    spec = importlib.util.spec_from_file_location(
        "check_no_print",
        Path(__file__).resolve().parents[1] / "scripts" / "check_no_print.py",
    )
    shim = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(shim)
    lines = shim.find_violations(pkg)
    assert len(lines) == 3 and not any("cli.py" in v for v in lines)
    assert any(v.startswith("core/mod.py:") for v in lines)
    assert any(v.startswith("core2/mod.py:") for v in lines)


def test_nested_hotpath_marks_report_once():
    src = (
        "# dsst: hotpath\n"
        "def hot(q):\n"
        "    # dsst: hotpath\n"
        "    while True:\n"
        "        q.item()\n"
    )
    findings = lint_text(HostSyncChecker(), src)
    assert len(findings) == 1, [f.text() for f in findings]


def test_corrupt_baseline_is_a_usage_error(tmp_path):
    bl = tmp_path / "baseline.json"
    bl.write_text("<<<<<<< not json")
    with pytest.raises(LintUsageError):
        run_lint(["no-print"], baseline_path=bl)
    from dss_ml_at_scale_tpu.config.cli import main

    assert main(["lint", "--baseline", str(bl)]) == 2


def test_subset_update_preserves_other_rules_entries(tmp_path):
    """--rules subset --update-baseline must not wipe entries belonging
    to rules it never re-checked (regression: it rewrote wholesale)."""
    import json
    import shutil

    from dss_ml_at_scale_tpu.analysis import DEFAULT_BASELINE
    from dss_ml_at_scale_tpu.config.cli import main

    bl = tmp_path / "baseline.json"
    shutil.copy(DEFAULT_BASELINE, bl)
    before = load_baseline(bl)
    assert before, "committed baseline unexpectedly empty"
    rc = main([
        "lint", "--rules", "no-print", "--update-baseline",
        "--baseline", str(bl), "--reason", "unused",
    ])
    assert rc == 0
    after = load_baseline(bl)
    assert after == before, (
        "subset update dropped entries: "
        + json.dumps(sorted(set(before) - set(after)))
    )


def test_docstring_mention_of_directive_is_inert():
    """A docstring LINE spelling the directive syntax must not mint a
    phantom suppression/hotpath mark or a reasonless-suppression
    finding (regression: raw-line regex scan)."""
    src = (
        '"""Docs.\n'
        "\n"
        "# dsst: ignore[no-print]\n"
        "# dsst: hotpath\n"
        '"""\n'
        "\n"
        "def f(x):\n"
        "    print(x)\n"
    )
    findings = lint_text(NoPrintChecker(), src)
    # The print() is still flagged (docstring line 3 suppressed nothing)
    # and no 'suppression' finding appeared for the reasonless mention.
    assert [f.rule for f in findings] == ["no-print"]
    from dss_ml_at_scale_tpu.analysis.core import FileContext

    ctx = FileContext(Path("fixture.py"), "fixture.py", "package", src)
    assert ctx.reasonless == [] and ctx.hotpath_marks == set()


def test_registry_level_baseline_entry_expires(tmp_path):
    """A baselined finalize()-pass finding (path '<registry>') must go
    stale when it disappears (regression: staleness was gated on
    scanned file paths, which '<registry>' never is)."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text('maybe_fail("a.b")\n')
    bl = tmp_path / "baseline.json"
    roots = [("package", pkg)]
    known_with_dead = {"a.b": "doc", "dead.site": "doc"}
    res = run_lint(
        roots=roots, baseline_path=bl,
        checkers=[FaultSitesChecker(known=known_with_dead)],
    )
    assert len(res.findings) == 1  # dead.site has no call site
    write_baseline(bl, res.findings, {}, "site lands next PR")
    res2 = run_lint(
        roots=roots, baseline_path=bl,
        checkers=[FaultSitesChecker(known=known_with_dead)],
    )
    assert res2.findings == [] and res2.exit_code == 0
    # The registry entry is cleaned up: the baselined finding is gone
    # and its entry must now be reported stale.
    res3 = run_lint(
        roots=roots, baseline_path=bl,
        checkers=[FaultSitesChecker(known={"a.b": "doc"})],
    )
    assert res3.findings == []
    assert len(res3.stale_baseline) == 1 and res3.exit_code == 1


def test_baseline_entry_of_deleted_file_goes_stale(tmp_path):
    """Deleting a file must expire its baseline entries — otherwise a
    later re-added file with the same flagged line silently inherits
    the dead exemption (regression: staleness required a scanned
    path)."""
    # The fixture tree must live INSIDE the repo so run_lint can
    # attribute entries to the scanned root by repo-relative prefix.
    import shutil
    import uuid

    repo_tmp = (
        Path(__file__).resolve().parents[1]
        / f"_lint_tmp_{uuid.uuid4().hex[:8]}"
    )
    pkg = repo_tmp / "pkg"
    pkg.mkdir(parents=True)
    try:
        (pkg / "mod.py").write_text("def f(x):\n    print(x)\n")
        bl = tmp_path / "baseline.json"
        roots = [("package", pkg)]
        res = run_lint(["no-print"], roots=roots, baseline_path=bl)
        write_baseline(bl, res.findings, {}, "pending")
        (pkg / "mod.py").unlink()
        res2 = run_lint(["no-print"], roots=roots, baseline_path=bl)
        assert res2.findings == []
        assert len(res2.stale_baseline) == 1 and res2.exit_code == 1
    finally:
        shutil.rmtree(repo_tmp)


def test_hotpath_loop_header_is_checked():
    """A sync in the marked loop's CONDITION runs every iteration and
    must be flagged (regression: only the body was scanned)."""
    src = (
        "def f(done, q):\n"
        "    # dsst: hotpath\n"
        "    while not done.item():\n"
        "        q.put(1)\n"
    )
    findings = lint_text(HostSyncChecker(), src)
    assert len(findings) == 1 and ".item()" in findings[0].message


def test_workerpool_concurrent_drop_close_no_crash():
    """close() racing drop() must neither lose heartbeat threads nor
    join an unstarted one (regression for both halves of the fix)."""
    import threading

    from dss_ml_at_scale_tpu.resilience.workers import WorkerPool

    for _ in range(20):
        pool = WorkerPool(
            ["a", "b", "c"], probe=lambda w: None,
            heartbeat_interval=0.01, dead_grace=0.1,
        )
        ts = [
            threading.Thread(target=pool.drop, args=(w,))
            for w in ("a", "b", "c")
        ]
        for t in ts:
            t.start()
        pool.close()  # races the drops; must not raise
        for t in ts:
            t.join()
        pool.close()  # idempotent


# -- CLI ----------------------------------------------------------------------


def test_cli_lint_clean_and_json(capsys):
    import json

    from dss_ml_at_scale_tpu.config.cli import main

    assert main(["lint", "--rules", "no-print,bare-except"]) == 0
    assert main(["lint", "--rules", "no-print", "--json"]) == 0
    out = capsys.readouterr().out
    payload = json.loads(out[out.index("{"):])
    assert payload["version"] == 1 and payload["ok"] is True
    assert main(["lint", "--rules", "not-a-rule"]) == 2


def test_cli_lint_list_rules(capsys):
    from dss_ml_at_scale_tpu.config.cli import main

    assert main(["lint", "--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in ("trace-safety", "retrace-hazard", "host-sync",
                 "lock-discipline", "telemetry-registry", "no-print",
                 "bare-except", "fault-sites"):
        assert rule in out


# -- dsst lint --changed ------------------------------------------------------


def test_changed_paths_scope_the_scan(tmp_path):
    """An explicit file list lints exactly those files — the fast
    pre-commit mode — with per-root rule scoping intact."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "a.py").write_text("def f(x):\n    print(x)\n")
    (pkg / "b.py").write_text("def g(x):\n    print(x)\n")
    roots = [("package", pkg)]
    bl = tmp_path / "baseline.json"
    full = run_lint(["no-print"], roots=roots, baseline_path=bl)
    assert len(full.findings) == 2
    sub = run_lint(
        ["no-print"], roots=roots, baseline_path=bl,
        paths=[pkg / "a.py"],
    )
    assert [f.path for f in sub.findings] == ["a.py"]


def test_changed_ignores_files_outside_every_root(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    foreign = tmp_path / "foreign.py"
    foreign.write_text("print('not ours')\n")
    res = run_lint(
        ["no-print"], roots=[("package", pkg)],
        baseline_path=tmp_path / "baseline.json", paths=[foreign],
    )
    assert res.findings == []


def test_changed_drops_full_scan_only_checkers(tmp_path):
    """Registry-reconciling rules (telemetry-registry, fault-sites)
    misfire on partial scans — the default all-rules run must skip
    them, not report every out-of-scope call site as a dead registry
    entry."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "a.py").write_text("def f(x):\n    return x\n")
    res = run_lint(
        None,
        roots=[("package", pkg)],
        baseline_path=tmp_path / "baseline.json",
        paths=[pkg / "a.py"],
    )
    assert "telemetry-registry" not in res.rules
    assert "fault-sites" not in res.rules
    assert "no-print" in res.rules
    assert res.findings == []


def test_changed_explicit_full_scan_only_rule_is_a_usage_error(tmp_path):
    """Silently skipping a rule the user NAMED would report a clean
    pass for a check that never ran — that has to be exit 2, not 0."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "a.py").write_text("def f(x):\n    return x\n")
    with pytest.raises(LintUsageError, match="full registry"):
        run_lint(
            ["telemetry-registry", "no-print"],
            roots=[("package", pkg)],
            baseline_path=tmp_path / "baseline.json",
            paths=[pkg / "a.py"],
        )


def test_changed_does_not_stale_unscanned_baseline_entries(tmp_path):
    """A partial scan can't prove an out-of-scope baseline entry stale."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "a.py").write_text("def f(x):\n    print(x)\n")
    (pkg / "b.py").write_text("def g(x):\n    return x\n")
    roots = [("package", pkg)]
    bl = tmp_path / "baseline.json"
    res = run_lint(["no-print"], roots=roots, baseline_path=bl)
    write_baseline(bl, res.findings, {}, "accepted for the fixture")
    # Fix a.py, then scan ONLY b.py: the now-stale entry for a.py is
    # out of scope and must not fail the partial run.
    (pkg / "a.py").write_text("def f(x):\n    return x\n")
    sub = run_lint(
        ["no-print"], roots=roots, baseline_path=bl, paths=[pkg / "b.py"]
    )
    assert sub.findings == [] and sub.stale_baseline == []
    # The full scan still catches it — staleness is a full-suite truth.
    full = run_lint(["no-print"], roots=roots, baseline_path=bl)
    assert len(full.stale_baseline) == 1


def test_cli_changed_rejects_update_baseline():
    from dss_ml_at_scale_tpu.config.cli import main

    rc = main([
        "lint", "--changed", "--update-baseline", "--reason", "nope",
    ])
    assert rc == 2


def test_cli_changed_json_is_json_even_with_no_changes(
    monkeypatch, capsys
):
    """--json promises one parseable document on stdout; an empty
    change set must not degrade it to a prose line."""
    import json

    from dss_ml_at_scale_tpu.config import commands
    from dss_ml_at_scale_tpu.config.cli import main

    monkeypatch.setattr(
        commands, "_changed_python_files", lambda ref: []
    )
    assert main(["lint", "--changed", "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["counts"]["active"] == 0


def test_cli_changed_runs_against_the_repo():
    """`dsst lint --changed` on the real checkout: whatever is dirty vs
    HEAD must be lint-clean (the full-suite gate already guarantees the
    superset, so this is about the plumbing: git scoping, root
    attribution, full-scan-only skipping)."""
    import subprocess

    from dss_ml_at_scale_tpu.analysis.core import REPO_ROOT
    from dss_ml_at_scale_tpu.config.cli import main

    probe = subprocess.run(
        ["git", "rev-parse", "HEAD"], cwd=REPO_ROOT,
        capture_output=True, text=True,
    )
    if probe.returncode != 0:
        pytest.skip("not a git checkout")
    assert main(["lint", "--changed"]) == 0

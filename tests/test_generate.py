"""KV-cached autoregressive generation (models/transformer.py).

The LM track trains long-context models (flash/ring attention); this
covers the inference half: a lax.scan decode loop over per-layer K/V
caches whose parameter tree is IDENTICAL to the training path, so any
trained checkpoint decodes without conversion.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dss_ml_at_scale_tpu.models import TransformerLM, generate, init_kv_cache


def tiny_lm(**kw):
    cfg = dict(vocab_size=31, dim=32, num_heads=4, num_layers=2,
               max_seq=24, attention="reference", dtype=jnp.float32)
    cfg.update(kw)
    return TransformerLM(**cfg)


@pytest.fixture(scope="module")
def lm_and_params():
    model = tiny_lm()
    tokens = jnp.zeros((1, 8), jnp.int32)
    variables = model.init(jax.random.key(0), tokens)
    return model, variables


def test_decode_step_matches_full_forward(lm_and_params):
    """The load-bearing parity: stepping tokens one at a time through
    the KV cache reproduces the full-context causal forward's logits at
    every position (same params, same math, different program)."""
    model, variables = lm_and_params
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, 31, (2, 10)), jnp.int32)

    full = model.apply(variables, tokens)  # [2, 10, vocab]

    cache = init_kv_cache(model, 2)
    stepped = []
    for t in range(10):
        logits, cache = model.apply(
            variables, tokens[:, t:t + 1], cache=cache, pos=t
        )
        stepped.append(logits)
    stepped = jnp.stack(stepped, axis=1)
    np.testing.assert_allclose(
        np.asarray(stepped), np.asarray(full), rtol=2e-4, atol=2e-4
    )


def test_prefill_matches_full_forward_and_feeds_decode(lm_and_params):
    """Chunked prefill (whole prompt, one causal pass, cache written)
    returns the same logits as the plain forward, and a decode step
    continuing from the prefilled cache equals the stepped-from-scratch
    path."""
    model, variables = lm_and_params
    rng = np.random.default_rng(3)
    tokens = jnp.asarray(rng.integers(0, 31, (2, 7)), jnp.int32)

    full = model.apply(variables, tokens)
    cache = init_kv_cache(model, 2)
    prefill, cache_p = model.apply(variables, tokens, cache=cache, pos=0)
    np.testing.assert_allclose(
        np.asarray(prefill), np.asarray(full), rtol=2e-4, atol=2e-4
    )

    # Continue one token from the prefilled cache vs from a cache built
    # token by token: identical logits.
    cache_s = init_kv_cache(model, 2)
    for t in range(7):
        _, cache_s = model.apply(
            variables, tokens[:, t:t + 1], cache=cache_s, pos=t
        )
    nxt = jnp.full((2, 1), 11, jnp.int32)
    l_p, _ = model.apply(variables, nxt, cache=cache_p, pos=7)
    l_s, _ = model.apply(variables, nxt, cache=cache_s, pos=7)
    np.testing.assert_allclose(
        np.asarray(l_p), np.asarray(l_s), rtol=2e-4, atol=2e-4
    )


def test_greedy_generate_matches_argmax_chain(lm_and_params):
    """temperature=0 generation equals manually chaining argmax through
    repeated FULL-context forwards — proving prefill, cache reuse, and
    the prompt/sample seam agree with the definitionally-correct path."""
    model, variables = lm_and_params
    prompt = jnp.asarray([[3, 7, 1]], jnp.int32)
    out = generate(model, variables, prompt, n_tokens=5)
    assert out.shape == (1, 8)
    assert np.array_equal(np.asarray(out[:, :3]), np.asarray(prompt))

    seq = prompt
    for _ in range(5):
        logits = model.apply(variables, seq)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))


def test_generate_is_jittable_and_batched(lm_and_params):
    model, variables = lm_and_params
    prompt = jnp.asarray([[1, 2], [9, 4], [0, 0]], jnp.int32)
    fn = jax.jit(
        lambda v, p: generate(model, v, p, n_tokens=4, temperature=0.0)
    )
    out = fn(variables, prompt)
    assert out.shape == (3, 6)
    # Rows decode independently: row 0 alone gives the same tokens.
    solo = generate(model, variables, prompt[:1], n_tokens=4)
    np.testing.assert_array_equal(np.asarray(out[:1]), np.asarray(solo))


def test_sampling_temperature_and_top_k(lm_and_params):
    model, variables = lm_and_params
    prompt = jnp.asarray([[5, 5]], jnp.int32)
    a = generate(model, variables, prompt, n_tokens=6, temperature=1.0,
                 rng=jax.random.key(1))
    b = generate(model, variables, prompt, n_tokens=6, temperature=1.0,
                 rng=jax.random.key(1))
    c = generate(model, variables, prompt, n_tokens=6, temperature=1.0,
                 rng=jax.random.key(2))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))  # same key
    assert not np.array_equal(np.asarray(a), np.asarray(c))  # diff key
    # top_k=1 at any temperature is greedy.
    g = generate(model, variables, prompt, n_tokens=6)
    k1 = generate(model, variables, prompt, n_tokens=6, temperature=2.0,
                  top_k=1, rng=jax.random.key(3))
    np.testing.assert_array_equal(np.asarray(g), np.asarray(k1))


def test_single_token_prompt(lm_and_params):
    """p=1 prefill returns the decode-step logits shape; generation
    still matches the chained-argmax ground truth."""
    model, variables = lm_and_params
    prompt = jnp.asarray([[9]], jnp.int32)
    out = generate(model, variables, prompt, n_tokens=4)
    assert out.shape == (1, 5)
    seq = prompt
    for _ in range(4):
        logits = model.apply(variables, seq)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))


def test_flash_model_generates_at_awkward_prompt_lengths():
    """A flash-attention model must generate for prompt lengths the
    kernel's block constraints reject — the prefill falls back to the
    reference path (same numbers, any shape)."""
    model = tiny_lm(attention="flash", max_seq=400)
    tokens = jnp.zeros((1, 8), jnp.int32)
    variables = model.init(jax.random.key(0), tokens)
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, 31, (1, 300)), jnp.int32
    )
    out = generate(model, variables, prompt, n_tokens=3)
    assert out.shape == (1, 303)
    # Ground truth via the reference model (same params).
    ref = generate(model.clone(attention="reference"), variables, prompt,
                   n_tokens=3)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_multi_token_cached_call_requires_pos_zero(lm_and_params):
    model, variables = lm_and_params
    cache = init_kv_cache(model, 1)
    with pytest.raises(ValueError, match="prefill only"):
        model.apply(variables, jnp.zeros((1, 3), jnp.int32), cache=cache,
                    pos=2)


def test_n_tokens_zero_returns_prompt(lm_and_params):
    model, variables = lm_and_params
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    out = generate(model, variables, prompt, n_tokens=0)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(prompt))


def test_moe_lm_generates():
    """Expert-parallel (MoE-FFN) models decode through the same cache
    path. No strict parity against the full forward here — top-1
    routing capacity can drop tokens in a parallel pass that a
    one-token decode always keeps (the standard MoE train/infer
    discrepancy) — but generation must run, be key-deterministic, and
    produce in-vocab tokens."""
    model = tiny_lm(ffn="moe", num_experts=4)
    variables = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))
    prompt = jnp.asarray([[3, 1, 2]], jnp.int32)
    a = generate(model, variables, prompt, n_tokens=5)
    b = generate(model, variables, prompt, n_tokens=5)
    assert a.shape == (1, 8)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert np.asarray(a).max() < 31 and np.asarray(a).min() >= 0


def test_budget_and_ring_guards(lm_and_params):
    model, variables = lm_and_params
    prompt = jnp.zeros((1, 20), jnp.int32)
    with pytest.raises(ValueError, match="max_seq"):
        generate(model, variables, prompt, n_tokens=10)  # 30 > 24

    ring = tiny_lm(attention="ring")
    cache = init_kv_cache(ring, 1)
    with pytest.raises(ValueError, match="ring"):
        ring.apply(variables, jnp.zeros((1, 1), jnp.int32), cache=cache,
                   pos=0)


def test_trained_lm_generates_from_its_training_distribution(devices8):
    """End to end: train a tiny LM on the seeded Markov stream through
    the Trainer, then greedy-generate — generated transitions must be
    plausible under the TRUE chain (a peaky Dirichlet makes rows
    near-deterministic), proving trained checkpoints drive the decode
    path."""
    import optax

    from dss_ml_at_scale_tpu.datagen.tokens import (
        TokenStreamConfig,
        token_batches,
        transition_matrix,
    )
    from dss_ml_at_scale_tpu.parallel import LMTask, Trainer, TrainerConfig
    from dss_ml_at_scale_tpu.runtime import make_mesh

    cfg = TokenStreamConfig(vocab_size=16, batch_size=16, seq_len=24,
                            concentration=0.02, seed=5)
    model = tiny_lm(vocab_size=16, max_seq=24)
    task = LMTask(model=model, tx=optax.adam(3e-3))
    trainer = Trainer(
        TrainerConfig(max_epochs=2, steps_per_epoch=40, log_every_steps=1000),
        mesh=make_mesh(),
    )
    result = trainer.fit(task, token_batches(cfg, num_batches=80))
    assert result.history[-1]["train_loss"] < result.history[0]["train_loss"]

    variables = {"params": result.state.params}
    first = next(token_batches(cfg, num_batches=1, sample_seed=99))
    prompt = jnp.asarray(first["tokens"][:1, :4], jnp.int32)
    out = np.asarray(generate(model, variables, prompt, n_tokens=12))

    t = transition_matrix(cfg)
    probs = [
        t[int(out[0, i]), int(out[0, i + 1])]
        for i in range(3, out.shape[1] - 1)
    ]
    # Greedy decode through a trained model should ride high-probability
    # transitions of the true chain — far above the uniform 1/16.
    assert np.mean(probs) > 0.3, (out, probs)
"""Profiling utilities: StepTimer math, trace capture, Trainer hook."""

import jax
import jax.numpy as jnp

from dss_ml_at_scale_tpu.parallel import ClassifierTask, Trainer, TrainerConfig
from dss_ml_at_scale_tpu.runtime import make_mesh
from dss_ml_at_scale_tpu.utils import StepTimer, annotate, trace

from test_models import tiny_resnet
from test_trainer import synthetic_batches


def test_step_timer_summary():
    t = StepTimer()
    assert t.summary() == {}
    t.tick()  # opens the first interval
    import time

    for _ in range(5):
        time.sleep(0.001)
        t.tick()
    s = t.summary()
    assert set(s) == {
        "step_time_mean_s",
        "step_time_p50_s",
        "step_time_p90_s",
        "step_time_max_s",
        "steps_per_sec",
    }
    assert s["step_time_mean_s"] >= 0.001
    assert s["step_time_max_s"] >= s["step_time_p50_s"]
    assert s["steps_per_sec"] > 0
    t.reset()
    assert t.summary() == {}


def test_step_timer_capacity_bounded():
    t = StepTimer(capacity=10)
    for _ in range(50):
        t.tick()
    assert len(t.intervals) == 10


def test_step_timer_skips_compile_interval_at_recorder():
    # The first interval after construction (the compile step) is dropped
    # when recorded, so later ring-buffer eviction can't resurrect it and
    # per-epoch resets don't silently discard a real step.
    t = StepTimer()
    for _ in range(4):
        t.tick()
    assert len(t.intervals) == 2  # 3 intervals ticked, compile one dropped
    t.reset()  # epoch >= 2: no compile step, nothing skipped
    for _ in range(4):
        t.tick()
    assert len(t.intervals) == 3
    t.reset(skip_next_interval=True)  # caller knows a recompile is coming
    for _ in range(4):
        t.tick()
    assert len(t.intervals) == 2


def test_trace_writes_profile(tmp_path):
    logdir = tmp_path / "trace"
    with trace(str(logdir)):
        with annotate("square"):
            x = jax.jit(lambda v: v * v)(jnp.arange(8.0))
            jax.block_until_ready(x)
    # jax.profiler writes plugins/profile/<ts>/*.xplane.pb under logdir.
    produced = list(logdir.rglob("*.xplane.pb"))
    assert produced, f"no trace output under {logdir}"


def test_trainer_profile_hook(devices8, tmp_path):
    import optax

    task = ClassifierTask(model=tiny_resnet(num_classes=4), tx=optax.adam(1e-2))
    profile_dir = tmp_path / "prof"
    trainer = Trainer(
        TrainerConfig(
            max_epochs=1,
            steps_per_epoch=8,
            log_every_steps=1000,
            profile_dir=str(profile_dir),
            profile_start_step=2,
            profile_num_steps=3,
        ),
        mesh=make_mesh(),
    )
    result = trainer.fit(task, iter(synthetic_batches(8)))
    assert list(profile_dir.rglob("*.xplane.pb")), "trainer trace not captured"
    # Per-step timing lands in the epoch summary.
    assert "step_time_mean_s" in result.history[0]
    assert result.history[0]["steps_per_sec"] > 0

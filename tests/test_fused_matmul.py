"""Parity suite for the Pallas fused BN-apply + 1x1-conv matmul
(ops/fused_matmul.py) — the second HBM byte-cutting lever.

Same standard as test_fused_norm: the fused op must match the
*unfused reference composition* (HLO batch-norm -> relu -> matmul,
differentiated by plain autodiff through the statistics) in forward,
in every cotangent (dy, dgamma, dbeta, dW, dresidual — including the
internalized mean/var stats path), in running-statistics updates at
the model level, and in eval mode.  Runs in Pallas interpret mode on
the CPU backend (tests/conftest.py forces cpu); the same kernels
compile for TPU.

Capability parity target: torchvision Bottleneck's
``conv1x1 ∘ relu ∘ BatchNorm2d`` inside the reference's fine-tuned
ResNet-50 (``deep_learning/2.distributed-data-loading-petastorm.py``
:135-165).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dss_ml_at_scale_tpu.ops.fused_matmul import bn_relu_matmul

EPS = 1e-5


@pytest.fixture
def rng():
    return np.random.default_rng(42)


def _reference(y, gamma, beta, w, residual=None):
    """Plain-HLO composition, stats differentiated by autodiff."""
    k = y.shape[-1]
    yf = y.reshape(-1, k).astype(jnp.float32)
    mean = jnp.mean(yf, 0)
    var = jnp.mean(jnp.square(yf), 0) - jnp.square(mean)
    a = (y.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + EPS)
    a = a * gamma + beta
    if residual is not None:
        a = a + residual.astype(jnp.float32)
    a = jnp.maximum(a, 0.0)
    out = a.reshape(-1, k) @ w
    return out.reshape(*y.shape[:-1], w.shape[1])


def _fused(y, gamma, beta, w, residual=None):
    k = y.shape[-1]
    yf = y.reshape(-1, k).astype(jnp.float32)
    mean = jnp.mean(yf, 0)
    var = jnp.mean(jnp.square(yf), 0) - jnp.square(mean)
    return bn_relu_matmul(
        y, gamma, beta, mean, var, w, eps=EPS, residual=residual
    )


def _inputs(rng, shape=(4, 6, 6, 24), n=40):
    k = shape[-1]
    y = jnp.asarray(rng.normal(size=shape), jnp.float32)
    res = jnp.asarray(rng.normal(size=shape), jnp.float32)
    gamma = jnp.asarray(rng.normal(1.0, 0.2, k), jnp.float32)
    beta = jnp.asarray(rng.normal(0.0, 0.2, k), jnp.float32)
    w = jnp.asarray(rng.normal(0.0, 0.1, (k, n)), jnp.float32)
    return y, res, gamma, beta, w


@pytest.mark.parametrize("with_res", [False, True])
def test_forward_matches_reference(rng, with_res):
    y, res, gamma, beta, w = _inputs(rng)
    r = res if with_res else None
    np.testing.assert_allclose(
        _fused(y, gamma, beta, w, r), _reference(y, gamma, beta, w, r),
        rtol=1e-5, atol=1e-5,
    )


@pytest.mark.parametrize("with_res", [False, True])
def test_gradients_match_reference(rng, with_res):
    """Every cotangent, including the internalized stats path: the
    reference differentiates through mean/var as functions of y, so a
    match here proves the custom VJP's (sum_g + x_hat*sum_gx)/n
    correction is the true statistics backward."""
    y, res, gamma, beta, w = _inputs(rng)
    r = res if with_res else None

    def loss(fn):
        def inner(args):
            out = fn(*args[:4], args[4] if with_res else None)
            return jnp.sum(jnp.sin(out))  # nonconstant cotangent
        return inner

    args = (y, gamma, beta, w, res)
    g_ref = jax.grad(loss(_reference))(args)
    g_fus = jax.grad(loss(_fused))(args)
    names = ("dy", "dgamma", "dbeta", "dw", "dres")
    for name, a, b in zip(names, g_ref, g_fus):
        if name == "dres" and not with_res:
            continue
        scale = float(jnp.max(jnp.abs(a))) + 1e-9
        err = float(jnp.max(jnp.abs(a - b))) / scale
        assert err < 1e-5, f"{name}: rel err {err}"


def test_awkward_shapes_pad_correctly(rng):
    """K, N, M all non-multiples of the tile sizes: padding must be
    semantically inert in forward and backward."""
    y, res, gamma, beta, w = _inputs(rng, shape=(3, 5, 7, 17), n=33)

    np.testing.assert_allclose(
        _fused(y, gamma, beta, w), _reference(y, gamma, beta, w),
        rtol=1e-5, atol=1e-5,
    )
    g1 = jax.grad(lambda t: jnp.sum(_reference(t, gamma, beta, w)))(y)
    g2 = jax.grad(lambda t: jnp.sum(_fused(t, gamma, beta, w)))(y)
    np.testing.assert_allclose(g1, g2, rtol=1e-4, atol=1e-5)


def test_running_stats_eval_mode(rng):
    """Eval uses running statistics: same op, stats from outside."""
    y, _, gamma, beta, w = _inputs(rng)
    ra_mean = jnp.asarray(rng.normal(0, 0.5, y.shape[-1]), jnp.float32)
    ra_var = jnp.asarray(rng.uniform(0.5, 2.0, y.shape[-1]), jnp.float32)
    out = bn_relu_matmul(y, gamma, beta, ra_mean, ra_var, w, eps=EPS)
    a = (y - ra_mean) * jax.lax.rsqrt(ra_var + EPS) * gamma + beta
    expect = jnp.maximum(a, 0.0).reshape(-1, y.shape[-1]) @ w
    np.testing.assert_allclose(
        out.reshape(-1, w.shape[1]), expect, rtol=1e-5, atol=1e-5
    )


def test_constant_stats_gradients(rng):
    """Eval / frozen-BN: with ``batch_stats=False`` the stats are
    constants and dy must match autodiff through the constant-stats
    composition (no statistics correction)."""
    y, _, gamma, beta, w = _inputs(rng)
    k = y.shape[-1]
    ra_m = jnp.asarray(rng.normal(0, 0.5, k), jnp.float32)
    ra_v = jnp.asarray(rng.uniform(0.5, 2.0, k), jnp.float32)

    def ref(t):
        a = (t - ra_m) * jax.lax.rsqrt(ra_v + EPS) * gamma + beta
        return jnp.sum(jnp.sin(
            jnp.maximum(a, 0.0).reshape(-1, k) @ w
        ))

    def fused(t):
        return jnp.sum(jnp.sin(bn_relu_matmul(
            t, gamma, beta, ra_m, ra_v, w, eps=EPS, batch_stats=False
        )))

    g1, g2 = jax.grad(ref)(y), jax.grad(fused)(y)
    err = float(jnp.max(jnp.abs(g1 - g2))) / float(jnp.max(jnp.abs(g1)))
    assert err < 1e-5, f"eval dy rel err {err}"


def test_basic_block_models_reject_pallas():
    from dss_ml_at_scale_tpu.models.resnet import ResNet18

    m = ResNet18(num_classes=4, num_filters=8, dtype=jnp.float32,
                 fused_bn="pallas")
    with pytest.raises(ValueError, match="BottleneckBlock"):
        m.init(jax.random.key(0), jnp.zeros((1, 16, 16, 3)))


def test_pallas_mesh_requires_pallas_level():
    from jax.sharding import Mesh

    from dss_ml_at_scale_tpu.models.resnet import ResNet, ResNetBlock

    m = ResNet(stage_sizes=[1, 1], block_cls=ResNetBlock, num_classes=4,
               num_filters=8, dtype=jnp.float32, fused_bn=True,
               pallas_mesh=Mesh(jax.devices(), ("data",)))
    with pytest.raises(ValueError, match="pallas_mesh"):
        m.init(jax.random.key(0), jnp.zeros((1, 16, 16, 3)))


def test_conv_kernel_4d_accepted(rng):
    y, _, gamma, beta, w = _inputs(rng)
    k = y.shape[-1]
    w4 = w.reshape(1, 1, k, -1)
    np.testing.assert_allclose(
        _fused(y, gamma, beta, w4), _fused(y, gamma, beta, w),
        rtol=1e-6, atol=1e-6,
    )
    with pytest.raises(ValueError):
        bn_relu_matmul(y, gamma, beta, gamma, gamma,
                       jnp.zeros((3, 3, k, 8)))


def test_bf16_pipeline(rng):
    """bf16 activations / f32 params — the accelerator configuration.
    Tolerances are bf16-scale."""
    y, res, gamma, beta, w = _inputs(rng)
    yb, resb, wb = (y.astype(jnp.bfloat16), res.astype(jnp.bfloat16),
                    w.astype(jnp.bfloat16))
    out = _fused(yb, gamma, beta, wb, resb)
    assert out.dtype == jnp.bfloat16
    ref = _reference(y, gamma, beta, w, res)
    np.testing.assert_allclose(
        out.astype(jnp.float32), ref, rtol=0.05, atol=0.15
    )


def test_shard_map_batch_sharded_gradients(rng):
    """The SPMD form: op called per-shard inside shard_map over a
    batch-sharded mesh (the simulated 8-device slice), global stats
    passed in, ``axis_name=`` set.  Forward must equal the unsharded
    reference; every gradient — including dy's global-stats correction
    and the already-psummed dgamma/dbeta/dW — must match the
    single-device autodiff-through-stats reference."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from jax import shard_map

    n_dev = len(jax.devices())
    assert n_dev == 8, "conftest should provide the 8-device slice"
    B, H, W_, K, N = 16, 4, 4, 24, 40
    y = jnp.asarray(rng.normal(size=(B, H, W_, K)), jnp.float32)
    gamma = jnp.asarray(rng.normal(1.0, 0.2, K), jnp.float32)
    beta = jnp.asarray(rng.normal(0.0, 0.2, K), jnp.float32)
    w = jnp.asarray(rng.normal(0.0, 0.1, (K, N)), jnp.float32)
    mesh = Mesh(jax.devices(), ("data",))
    m_global = B * H * W_

    def stats(t):
        tf = t.reshape(-1, K).astype(jnp.float32)
        mean = jnp.mean(tf, 0)
        var = jnp.mean(jnp.square(tf), 0) - jnp.square(mean)
        return mean, var

    def sharded(y, gamma, beta, w):
        mean, var = stats(y)  # global stats, computed outside shard_map

        def per_shard(y_s, gamma, beta, mean, var, w):
            return bn_relu_matmul(
                y_s, gamma, beta, mean, var, w, eps=EPS,
                axis_name="data", global_count=m_global,
            )

        # check_vma=False: the varying-mesh-axes checker cannot see
        # through pallas_call's outputs.
        return shard_map(
            per_shard, mesh=mesh,
            in_specs=(P("data"), P(), P(), P(), P(), P()),
            out_specs=P("data"), check_vma=False,
        )(y, gamma, beta, mean, var, w)

    y_sh = jax.device_put(y, NamedSharding(mesh, P("data")))
    out = sharded(y_sh, gamma, beta, w)
    np.testing.assert_allclose(
        out, _reference(y, gamma, beta, w), rtol=1e-5, atol=1e-5
    )

    def loss_sharded(args):
        return jnp.sum(jnp.sin(sharded(*args)))

    def loss_ref(args):
        return jnp.sum(jnp.sin(_reference(*args)))

    g_sh = jax.grad(loss_sharded)((y_sh, gamma, beta, w))
    g_ref = jax.grad(loss_ref)((y, gamma, beta, w))
    for name, a, b in zip(("dy", "dgamma", "dbeta", "dw"), g_ref, g_sh):
        scale = float(jnp.max(jnp.abs(a))) + 1e-9
        err = float(jnp.max(jnp.abs(a - jnp.asarray(b)))) / scale
        assert err < 1e-5, f"{name}: rel err {err}"


# ---------------------------------------------------------------------------
# Model-level: the "pallas" fusion level of ResNet bottleneck blocks
# ---------------------------------------------------------------------------

def _tiny_resnet(fused):
    from dss_ml_at_scale_tpu.models.resnet import BottleneckBlock, ResNet

    return ResNet(
        stage_sizes=[1, 1], block_cls=BottleneckBlock, num_classes=7,
        num_filters=8, dtype=jnp.float32, fused_bn=fused,
    )


@pytest.fixture(scope="module")
def model_pair():
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(2, 16, 16, 3)), jnp.float32
    )
    m_ref = _tiny_resnet(True)       # HLO fused path (itself flax-proven)
    m_pal = _tiny_resnet("pallas")
    v = m_ref.init(jax.random.key(0), x)
    return m_ref, m_pal, v, x


def test_model_param_tree_identical(model_pair):
    m_ref, m_pal, v, x = model_pair
    v_pal = m_pal.init(jax.random.key(0), x)
    assert (jax.tree_util.tree_structure(v)
            == jax.tree_util.tree_structure(v_pal))
    assert jax.tree_util.tree_all(jax.tree_util.tree_map(
        lambda a, b: a.shape == b.shape, v, v_pal
    ))


def test_model_forward_and_stats_match(model_pair):
    m_ref, m_pal, v, x = model_pair
    lr, ur = m_ref.apply(v, x, train=True, mutable=["batch_stats"])
    lp, up = m_pal.apply(v, x, train=True, mutable=["batch_stats"])
    np.testing.assert_allclose(lr, lp, rtol=1e-5, atol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5,
                                                atol=1e-6),
        ur["batch_stats"], up["batch_stats"],
    )
    # Eval mode follows running stats the same way.
    np.testing.assert_allclose(
        m_ref.apply(v, x, train=False), m_pal.apply(v, x, train=False),
        rtol=1e-5, atol=1e-5,
    )


def test_model_eval_gradients_match(model_pair):
    """Frozen-BN gradients (train=False under grad — fine-tuning /
    saliency): the pallas path must match the HLO path, which
    differentiates the running-stats composition by plain autodiff."""
    m_ref, m_pal, v, x = model_pair

    def gsum(m):
        def f(t):
            return jnp.sum(m.apply(v, t, train=False))
        return jax.grad(f)(x)

    g_ref, g_pal = gsum(m_ref), gsum(m_pal)
    err = float(jnp.max(jnp.abs(g_ref - g_pal))) / (
        float(jnp.max(jnp.abs(g_ref))) + 1e-9
    )
    assert err < 1e-4, f"eval input-grad rel err {err}"


def test_model_sharded_pallas_mesh_gradients(model_pair):
    """The SPMD model form: ResNet(fused_bn="pallas", pallas_mesh=...)
    under a jitted step with the batch sharded over the 8-device mesh.
    Forward and parameter gradients must match the (unsharded) HLO
    fused reference — proving the shard_map-wrapped kernel site
    composes with the surrounding GSPMD program."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from dss_ml_at_scale_tpu.models.resnet import BottleneckBlock, ResNet

    m_ref, _, v, x = model_pair
    lbl = jnp.asarray([1, 3] * 4, jnp.int32)
    x8 = jnp.concatenate([x] * 4, axis=0)  # batch 8 -> shards evenly
    mesh = Mesh(jax.devices(), ("data",))
    m_sh = ResNet(
        stage_sizes=[1, 1], block_cls=BottleneckBlock, num_classes=7,
        num_filters=8, dtype=jnp.float32, fused_bn="pallas",
        pallas_mesh=mesh,
    )

    def loss_of(m, t):
        def f(params):
            lg, _ = m.apply(
                {"params": params, "batch_stats": v["batch_stats"]},
                t, train=True, mutable=["batch_stats"],
            )
            oh = jax.nn.one_hot(lbl, lg.shape[-1])
            return -jnp.mean(jnp.sum(oh * jax.nn.log_softmax(lg), -1))
        return f

    x_sharded = jax.device_put(
        x8, NamedSharding(mesh, P("data", None, None, None))
    )
    g_sh = jax.jit(jax.grad(loss_of(m_sh, x_sharded)))(v["params"])
    g_ref = jax.grad(loss_of(m_ref, x8))(v["params"])
    errs = jax.tree_util.tree_map(
        lambda a, b: float(
            jnp.max(jnp.abs(a - jnp.asarray(b)))
            / (jnp.max(jnp.abs(a)) + 1e-9)
        ),
        g_ref, g_sh,
    )
    worst = max(jax.tree_util.tree_leaves(errs))
    assert worst < 5e-4, f"worst sharded grad rel err {worst}"


def test_model_gradients_match(model_pair):
    m_ref, m_pal, v, x = model_pair
    lbl = jnp.asarray([1, 3], jnp.int32)

    def grads(m):
        def f(params):
            lg, _ = m.apply(
                {"params": params, "batch_stats": v["batch_stats"]},
                x, train=True, mutable=["batch_stats"],
            )
            oh = jax.nn.one_hot(lbl, lg.shape[-1])
            return -jnp.mean(jnp.sum(oh * jax.nn.log_softmax(lg), -1))
        return jax.grad(f)(v["params"])

    g_ref, g_pal = grads(m_ref), grads(m_pal)
    errs = jax.tree_util.tree_map(
        lambda a, b: float(
            jnp.max(jnp.abs(a - b)) / (jnp.max(jnp.abs(a)) + 1e-9)
        ),
        g_ref, g_pal,
    )
    worst = max(jax.tree_util.tree_leaves(errs))
    assert worst < 5e-4, f"worst grad rel err {worst}"

"""Pipeline parallelism (GPipe SPMD schedule) and MoE expert parallelism.

Both strategies are beyond-parity additions (SURVEY.md §2.3 lists PP and
EP as absent from the reference); these tests pin their correctness
against unsharded sequential execution on the 8-device simulated slice.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dss_ml_at_scale_tpu.models import MoEMLP, TransformerLM, collect_aux_loss, next_token_loss
from dss_ml_at_scale_tpu.parallel import (
    pipeline_utilization,
    spmd_pipeline,
    stack_stage_params,
    stage_sharding,
)


# ---------------------------------------------------------------------------
# Pipeline parallelism
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def pipe_mesh():
    return Mesh(np.array(jax.devices()).reshape(4, 2), ("pipe", "data"))


def _mlp_stage(params, x):
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    return h @ params["w2"] + params["b2"]


def _init_stage(rng, d=16, h=32):
    k1, k2 = jax.random.split(rng)
    return {
        "w1": jax.random.normal(k1, (d, h)) * 0.3,
        "b1": jnp.zeros((h,)),
        "w2": jax.random.normal(k2, (h, d)) * 0.3,
        "b2": jnp.zeros((d,)),
    }


def _sequential(stacked, xs, n_stages):
    out = xs
    for i in range(n_stages):
        params = jax.tree_util.tree_map(lambda l: l[i], stacked)
        out = jax.vmap(lambda mb: _mlp_stage(params, mb))(out)
    return out


def test_pipeline_matches_sequential(rng, pipe_mesh):
    n_stages = pipe_mesh.shape["pipe"]
    stacked = stack_stage_params(_init_stage, jax.random.key(0), n_stages)
    stacked = jax.device_put(stacked, stage_sharding(stacked, pipe_mesh, "pipe"))
    xs = jnp.asarray(rng.normal(size=(8, 4, 16)), jnp.float32)  # [M, mb, d]

    run = spmd_pipeline(_mlp_stage, pipe_mesh, "pipe")
    out = jax.jit(run)(stacked, xs)
    ref = _sequential(jax.device_get(stacked), xs, n_stages)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_pipeline_gradients_match_sequential(rng, pipe_mesh):
    n_stages = pipe_mesh.shape["pipe"]
    stacked = stack_stage_params(_init_stage, jax.random.key(1), n_stages)
    sharded = jax.device_put(stacked, stage_sharding(stacked, pipe_mesh, "pipe"))
    xs = jnp.asarray(rng.normal(size=(6, 4, 16)), jnp.float32)
    tgt = jnp.asarray(rng.normal(size=(6, 4, 16)), jnp.float32)

    run = spmd_pipeline(_mlp_stage, pipe_mesh, "pipe")

    def pipe_loss(p):
        return jnp.mean((run(p, xs) - tgt) ** 2)

    def seq_loss(p):
        return jnp.mean((_sequential(p, xs, n_stages) - tgt) ** 2)

    g_pipe = jax.jit(jax.grad(pipe_loss))(sharded)
    g_seq = jax.grad(seq_loss)(stacked)
    for a, b in zip(
        jax.tree_util.tree_leaves(g_pipe), jax.tree_util.tree_leaves(g_seq)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4)


def test_pipeline_trains(rng, pipe_mesh):
    # A few SGD steps through the pipelined loss must reduce it.
    n_stages = pipe_mesh.shape["pipe"]
    stacked = stack_stage_params(_init_stage, jax.random.key(2), n_stages)
    stacked = jax.device_put(stacked, stage_sharding(stacked, pipe_mesh, "pipe"))
    xs = jnp.asarray(rng.normal(size=(8, 4, 16)), jnp.float32)
    tgt = jnp.sin(xs)

    run = spmd_pipeline(_mlp_stage, pipe_mesh, "pipe")
    tx = optax.adam(1e-2)
    opt = tx.init(stacked)

    @jax.jit
    def step(p, opt):
        loss, g = jax.value_and_grad(
            lambda p: jnp.mean((run(p, xs) - tgt) ** 2)
        )(p)
        upd, opt = tx.update(g, opt)
        return optax.apply_updates(p, upd), opt, loss

    losses = []
    for _ in range(12):
        stacked, opt, loss = step(stacked, opt)
        losses.append(float(loss))
    assert losses[-1] < 0.5 * losses[0], losses


def test_pipeline_dp_composition(rng, pipe_mesh):
    # PP × DP: sharding the within-microbatch batch over "data" must not
    # change the math — same outputs and grads as the replicated run.
    n_stages = pipe_mesh.shape["pipe"]
    stacked = stack_stage_params(_init_stage, jax.random.key(3), n_stages)
    stacked = jax.device_put(stacked, stage_sharding(stacked, pipe_mesh, "pipe"))
    xs = jnp.asarray(rng.normal(size=(6, 4, 16)), jnp.float32)

    run_dp = spmd_pipeline(_mlp_stage, pipe_mesh, "pipe", batch_axis="data")
    out = jax.jit(run_dp)(stacked, xs)
    ref = _sequential(jax.device_get(stacked), xs, n_stages)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)

    g_dp = jax.jit(jax.grad(lambda p: jnp.mean(run_dp(p, xs) ** 2)))(stacked)
    g_ref = jax.grad(
        lambda p: jnp.mean(_sequential(p, xs, n_stages) ** 2)
    )(jax.device_get(stacked))
    for a, b in zip(
        jax.tree_util.tree_leaves(g_dp), jax.tree_util.tree_leaves(g_ref)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4)


def test_pipeline_utilization_accounting():
    assert pipeline_utilization(8, 4) == pytest.approx(8 / 11)
    assert pipeline_utilization(64, 4) > 0.95


# ---------------------------------------------------------------------------
# MoE / expert parallelism
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def expert_mesh():
    return Mesh(np.array(jax.devices()).reshape(8), ("expert",))


def test_moe_single_expert_equals_dense_mlp(rng):
    # With one expert and ample capacity, routing is the identity: the MoE
    # layer must compute exactly its expert's MLP (gate prob == 1).
    x = jnp.asarray(rng.normal(size=(2, 8, 16)), jnp.float32)
    moe = MoEMLP(num_experts=1, mlp_ratio=2, capacity_factor=2.0,
                 dtype=jnp.float32)
    variables = moe.init(jax.random.key(0), x)
    out, _ = moe.apply(variables, x, mutable=["intermediates"])

    p = variables["params"]
    tokens = x.reshape(-1, 16)
    ref = (
        jax.nn.gelu(tokens @ p["w_up"][0] + p["b_up"][0])
        @ p["w_down"][0]
        + p["b_down"][0]
    ).reshape(x.shape)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_moe_combine_weights_and_capacity(rng):
    # With generous capacity no token is dropped: every token's combine
    # weight sums to its chosen expert's gate probability (> 1/E).
    x = jnp.asarray(rng.normal(size=(1, 32, 8)), jnp.float32)
    moe = MoEMLP(num_experts=4, mlp_ratio=2, capacity_factor=4.0,
                 dtype=jnp.float32)
    variables = moe.init(jax.random.key(1), x)
    out, inter = moe.apply(variables, x, mutable=["intermediates"])
    assert np.isfinite(np.asarray(out)).all()
    aux = collect_aux_loss(inter["intermediates"])
    # Switch aux loss is >= 1 (perfect balance) and finite.
    assert float(aux) >= 0.99, float(aux)

    # Tight capacity drops tokens but never errors and stays finite.
    tight = MoEMLP(num_experts=4, mlp_ratio=2, capacity_factor=0.25,
                   dtype=jnp.float32)
    v2 = tight.init(jax.random.key(2), x)
    out2, _ = tight.apply(v2, x, mutable=["intermediates"])
    assert np.isfinite(np.asarray(out2)).all()


def test_moe_expert_parallel_matches_single_device(rng, expert_mesh):
    # The SAME params/program, expert-sharded over 8 devices, must produce
    # the single-device result (EP changes layout, not math).
    x = jnp.asarray(rng.normal(size=(2, 16, 8)), jnp.float32)
    plain = MoEMLP(num_experts=8, mlp_ratio=2, capacity_factor=2.0,
                   dtype=jnp.float32)
    variables = plain.init(jax.random.key(3), x)

    sharded = MoEMLP(num_experts=8, mlp_ratio=2, capacity_factor=2.0,
                     dtype=jnp.float32, mesh=expert_mesh, axis_name="expert")

    ref, _ = plain.apply(variables, x, mutable=["intermediates"])
    out, _ = jax.jit(
        lambda v, x: sharded.apply(v, x, mutable=["intermediates"])
    )(variables, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_moe_router_noise_reachable_through_lm(rng):
    # TransformerLM(router_noise=...) + deterministic=False + a "router"
    # rng must actually jitter routing: two rng keys give different
    # outputs, while deterministic=True ignores the noise.
    lm = TransformerLM(
        vocab_size=32, dim=16, num_heads=2, num_layers=1, max_seq=16,
        dtype=jnp.float32, attention="reference",
        ffn="moe", num_experts=4, router_noise=5.0,
    )
    tokens = jnp.asarray(rng.integers(0, 32, (1, 16)), jnp.int32)
    variables = lm.init(jax.random.key(0), tokens)

    def fwd(key, det):
        out = lm.apply(
            variables, tokens, deterministic=det,
            rngs={"router": key}, mutable=["intermediates"],
        )[0]
        return np.asarray(out)

    a, b = fwd(jax.random.key(1), False), fwd(jax.random.key(2), False)
    assert not np.allclose(a, b), "router noise had no effect"
    c, d = fwd(jax.random.key(1), True), fwd(jax.random.key(2), True)
    np.testing.assert_allclose(c, d)


def test_moe_capacity_ceil(rng):
    # C = ceil(tokens·cf / E). 10 tokens, 4 experts, cf=1.0 -> C=3
    # (a floor would give int(2.5)=2). Zeroed router logits tie-break to
    # expert 0 for every token, so exactly C tokens survive (dropped
    # tokens contribute exactly 0 — combine weight is zero).
    x = jnp.asarray(rng.normal(size=(1, 10, 8)), jnp.float32)
    moe = MoEMLP(num_experts=4, mlp_ratio=2, capacity_factor=1.0,
                 dtype=jnp.float32)
    variables = moe.init(jax.random.key(5), x)
    from flax.core import unfreeze

    params = unfreeze(variables["params"])
    params["router"]["kernel"] = jnp.zeros_like(params["router"]["kernel"])
    out, _ = moe.apply({"params": params}, x, mutable=["intermediates"])
    kept = int(np.sum(np.abs(np.asarray(out)[0]).sum(axis=-1) > 1e-12))
    assert kept == 3, f"capacity should keep ceil(10/4)=3 tokens, kept {kept}"


def test_moe_transformer_trains_with_aux_loss(rng, expert_mesh):
    # TransformerLM(ffn="moe") end-to-end: one Adam step on the combined
    # next-token + aux objective, experts sharded over the mesh.
    lm = TransformerLM(
        vocab_size=64, dim=32, num_heads=4, num_layers=2, max_seq=32,
        dtype=jnp.float32, attention="reference",
        ffn="moe", num_experts=8, expert_mesh=expert_mesh,
    )
    tokens = jnp.asarray(rng.integers(0, 64, (2, 32)), jnp.int32)
    params = lm.init(jax.random.key(4), tokens)["params"]
    tx = optax.adam(1e-3)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt):
        def loss_fn(p):
            logits, inter = lm.apply(
                {"params": p}, tokens, mutable=["intermediates"]
            )
            aux = collect_aux_loss(inter["intermediates"])
            return next_token_loss(logits, tokens) + 0.01 * aux, aux

        (loss, aux), g = jax.value_and_grad(loss_fn, has_aux=True)(params)
        upd, opt = tx.update(g, opt)
        return optax.apply_updates(params, upd), opt, loss, aux

    losses = []
    for _ in range(5):
        params, opt, loss, aux = step(params, opt)
        assert np.isfinite(float(loss)) and np.isfinite(float(aux))
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_pipelined_task_trains_under_trainer(rng, pipe_mesh):
    # PP rides the same Trainer machinery as DP/SP/EP: stage-sharded
    # params declared via the state_shardings hook, GPipe schedule inside
    # the jitted step, loss falls, and the fitted params really are
    # stage-sharded (not replicated).
    import optax

    from dss_ml_at_scale_tpu.parallel import PipelinedTask, Trainer, TrainerConfig

    task = PipelinedTask(
        _mlp_stage, _init_stage, pipe_mesh, "pipe", batch_axis="data",
        tx=optax.adam(3e-2),
    )

    def batches(seed, n):
        # One fixed batch repeated (like test_pipeline_trains): the test
        # is about the machinery, not generalization.
        r = np.random.default_rng(seed)
        xs = r.normal(size=(8, 4, 16)).astype(np.float32)
        for _ in range(n):
            yield {"x": xs, "y": np.sin(xs)}

    trainer = Trainer(
        TrainerConfig(
            max_epochs=2,
            steps_per_epoch=40,
            limit_val_batches=2,
            log_every_steps=1000,
            batch_specs={
                "x": P(None, "data"),
                "y": P(None, "data"),
            },
        ),
        mesh=pipe_mesh,
    )
    result = trainer.fit(
        task, batches(0, 80), val_data_factory=lambda: batches(99, 2)
    )
    assert len(result.history) == 2
    assert result.history[1]["train_loss"] < 0.6 * result.history[0]["train_loss"]
    # Eval ran through the same sharded path and produced a finite score
    # (train memorizes one batch, so val MAGNITUDE is uninformative).
    assert np.isfinite(result.history[1]["val_loss"])
    # Params are stage-sharded over "pipe", not replicated.
    leaf = jax.tree_util.tree_leaves(result.state.params)[0]
    assert not leaf.sharding.is_fully_replicated
    assert "pipe" in (leaf.sharding.spec[0] or ())


# ---------------------------------------------------------------------------
# Pipeline-parallel Transformer LM
# ---------------------------------------------------------------------------


def test_pipelined_lm_matches_sequential_blocks(rng, pipe_mesh):
    # The pipelined stack must compute exactly what applying the same
    # blocks in sequence computes (embed/head shared by construction).
    from dss_ml_at_scale_tpu.models import PipelinedLM

    lm = PipelinedLM(
        vocab_size=32, dim=16, num_heads=2, mesh=pipe_mesh,
        batch_axis="data", max_seq=12,
    )
    params = lm.init(jax.random.key(0))
    tokens = jnp.asarray(rng.integers(0, 32, (6, 2, 12)), jnp.int32)
    out = jax.jit(lm.apply)(params, tokens)
    assert out.shape == (6, 2, 12, 32)

    # Sequential reference using the same block module and params.
    def sequential(params, tokens):
        m, mb, s = tokens.shape
        x = params["tok"][tokens] + params["pos"][None, None, :s]
        x = x.reshape(m * mb, s, -1)
        for i in range(lm.n_stages):
            stage = jax.tree_util.tree_map(lambda l: l[i], params["stages"])
            x = lm._block.apply({"params": stage}, x)
        x = x.astype(jnp.float32)
        x = x * jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + 1e-6)
        x = x * params["norm_scale"]
        return (x @ params["head"]).reshape(m, mb, s, -1)

    ref = sequential(jax.device_get(params), tokens)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_pipelined_lm_trains_under_trainer(rng, pipe_mesh):
    # PP on the LM family through the standard Trainer: loss falls toward
    # the Markov source's entropy floor with stage-sharded layer params.
    import optax

    from dss_ml_at_scale_tpu.datagen.tokens import (
        TokenStreamConfig,
        entropy_floor,
        token_batches,
    )
    from dss_ml_at_scale_tpu.models import PipelinedLM, PipelinedLMTask
    from dss_ml_at_scale_tpu.parallel import Trainer, TrainerConfig

    stream = TokenStreamConfig(
        vocab_size=16, batch_size=8, seq_len=24, concentration=0.05, seed=0
    )

    def micro(batches):
        for b in batches:
            yield {"tokens": b["tokens"].reshape(4, 2, 24)}

    lm = PipelinedLM(
        vocab_size=16, dim=32, num_heads=2, mesh=pipe_mesh,
        batch_axis="data", max_seq=24,
    )
    task = PipelinedLMTask(model=lm, tx=optax.adam(1e-2))
    trainer = Trainer(
        TrainerConfig(
            max_epochs=2,
            steps_per_epoch=50,
            limit_val_batches=2,
            log_every_steps=1000,
            batch_specs={"tokens": P(None, "data")},
        ),
        mesh=pipe_mesh,
    )
    result = trainer.fit(
        task,
        micro(token_batches(stream)),
        val_data_factory=lambda: micro(
            token_batches(stream, num_batches=2, sample_seed=777)
        ),
    )
    assert len(result.history) == 2
    assert result.history[1]["val_loss"] < 0.75 * np.log(16)
    assert result.history[1]["val_loss"] > entropy_floor(stream) - 0.05
    # Stage params live on the pipe axis, not replicated.
    leaf = jax.tree_util.tree_leaves(result.state.params["stages"])[0]
    assert "pipe" in (leaf.sharding.spec[0] or ())


def test_moe_bf16_default_dtype(rng):
    # The layer's default (bf16, MXU-native) must route identically to
    # f32 (routing is f32 by construction) and produce finite outputs
    # close to the f32 compute.
    x = jnp.asarray(rng.normal(size=(2, 16, 8)), jnp.float32)
    moe16 = MoEMLP(num_experts=4, mlp_ratio=2, capacity_factor=2.0)
    assert moe16.dtype == jnp.bfloat16  # the documented default
    variables = moe16.init(jax.random.key(7), x)
    out16, _ = moe16.apply(variables, x, mutable=["intermediates"])
    assert np.isfinite(np.asarray(out16, np.float32)).all()

    moe32 = MoEMLP(num_experts=4, mlp_ratio=2, capacity_factor=2.0,
                   dtype=jnp.float32)
    out32, _ = moe32.apply(variables, x, mutable=["intermediates"])
    np.testing.assert_allclose(
        np.asarray(out16, np.float32), np.asarray(out32),
        atol=0.05, rtol=0.05,
    )


@pytest.mark.parametrize("n_micro", [1, 3, 8])
def test_pipeline_micro_count_edges(rng, pipe_mesh, n_micro):
    # n_micro < n_stages (deep bubble), == and > : the schedule must bank
    # exactly the n_micro real outputs in every regime.
    n_stages = pipe_mesh.shape["pipe"]
    stacked = stack_stage_params(_init_stage, jax.random.key(9), n_stages)
    stacked = jax.device_put(stacked, stage_sharding(stacked, pipe_mesh, "pipe"))
    xs = jnp.asarray(rng.normal(size=(n_micro, 4, 16)), jnp.float32)
    run = spmd_pipeline(_mlp_stage, pipe_mesh, "pipe")
    out = jax.jit(run)(stacked, xs)
    ref = _sequential(jax.device_get(stacked), xs, n_stages)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_pipeline_single_stage_degenerates_to_apply(rng):
    # n_stages == 1 (odd device counts fall back to pipe=1): the schedule
    # must reduce to plain per-microbatch application.
    mesh = Mesh(np.array(jax.devices()).reshape(1, 8), ("pipe", "data"))
    stacked = stack_stage_params(_init_stage, jax.random.key(11), 1)
    xs = jnp.asarray(rng.normal(size=(4, 8, 16)), jnp.float32)
    run = spmd_pipeline(_mlp_stage, mesh, "pipe", batch_axis="data")
    out = jax.jit(run)(stacked, xs)
    ref = _sequential(jax.device_get(stacked), xs, 1)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)

import io

import numpy as np
import pytest
from PIL import Image

from dss_ml_at_scale_tpu.data import TransformSpec, prefetch_to_mesh
from dss_ml_at_scale_tpu.data.transform import (
    IMAGENET_MEAN,
    IMAGENET_STD,
    decode_resize_crop,
    imagenet_transform_spec,
)
from dss_ml_at_scale_tpu.runtime import make_mesh


def _jpeg(w, h, color=(255, 0, 0)):
    buf = io.BytesIO()
    Image.new("RGB", (w, h), color).save(buf, format="JPEG")
    return buf.getvalue()


def test_decode_resize_crop_shapes():
    for w, h in [(640, 480), (480, 640), (100, 300), (224, 224)]:
        out = decode_resize_crop(_jpeg(w, h))
        assert out.shape == (3, 224, 224)
        assert out.dtype == np.float32
        assert 0.0 <= out.min() and out.max() <= 1.0


def test_imagenet_spec_normalizes():
    spec = imagenet_transform_spec()
    assert spec.layout == "hwc"  # TPU-native default: no device transpose
    batch = {
        "content": np.array([_jpeg(300, 260), _jpeg(260, 300, (0, 0, 255))], dtype=object),
        "label_index": np.array([3, 7]),
    }
    out = spec(batch)
    assert out["image"].shape == (2, 224, 224, 3)
    assert out["label"].tolist() == [3, 7]
    # red channel of a pure-red jpeg ≈ (1 - mean)/std after normalize
    red = out["image"][0, :, :, 0].mean()
    assert abs(red - (1.0 - IMAGENET_MEAN[0]) / IMAGENET_STD[0]) < 0.05


def test_imagenet_spec_chw_layout_matches_hwc():
    # torchvision-parity layout: same pixels, transposed.
    batch = {
        "content": np.array([_jpeg(300, 260)], dtype=object),
        "label_index": np.array([0]),
    }
    hwc = imagenet_transform_spec(layout="hwc")(batch)["image"]
    chw = imagenet_transform_spec(layout="chw")(batch)["image"]
    assert chw.shape == (1, 3, 224, 224)
    np.testing.assert_array_equal(chw, hwc.transpose(0, 3, 1, 2))


def test_prefetch_to_mesh_shards_batches(devices8):
    mesh = make_mesh()
    batches = [{"x": np.full((8, 2), i, np.float32)} for i in range(6)]
    out = list(prefetch_to_mesh(iter(batches), mesh, depth=3))
    assert len(out) == 6
    for i, b in enumerate(out):
        assert float(np.asarray(b["x"]).mean()) == i
        assert len(b["x"].sharding.device_set) == 8


def test_prefetch_depth_validation(devices8):
    with pytest.raises(ValueError):
        list(prefetch_to_mesh(iter([]), make_mesh(), depth=0))


def test_uint8_output_dtype_matches_float_path():
    # uint8 spec emits the exact quantized bytes; dividing by 255 and
    # normalizing must reproduce the float32 spec bit-for-bit (same
    # quantization point in both paths).
    batch = {
        "content": np.array([_jpeg(300, 260), _jpeg(260, 300, (0, 0, 255))],
                            dtype=object),
        "label_index": np.array([0, 1]),
    }
    f32 = imagenet_transform_spec(output_dtype="float32")(dict(batch))
    u8 = imagenet_transform_spec(output_dtype="uint8")(dict(batch))
    assert u8["image"].dtype == np.uint8
    renorm = (u8["image"].astype(np.float32) / 255.0 - IMAGENET_MEAN) / IMAGENET_STD
    np.testing.assert_allclose(renorm, f32["image"], rtol=0, atol=1e-6)


def test_uint8_task_normalizes_on_device(devices8):
    import jax
    import jax.numpy as jnp
    import optax

    from dss_ml_at_scale_tpu.parallel import ClassifierTask
    from test_models import tiny_resnet

    task = ClassifierTask(model=tiny_resnet(num_classes=4), tx=optax.adam(1e-3))
    rng = np.random.default_rng(0)
    raw = rng.integers(0, 256, (4, 32, 32, 3)).astype(np.uint8)
    labels = np.array([0, 1, 2, 3], np.int32)
    batch_u8 = {"image": raw, "label": labels}
    batch_f32 = {
        "image": ((raw.astype(np.float32) / 255.0 - IMAGENET_MEAN)
                  / IMAGENET_STD),
        "label": labels,
    }
    state = task.init_state(jax.random.key(0), batch_f32)
    _, m_u8 = task.train_step(state, batch_u8)
    _, m_f32 = task.train_step(state, batch_f32)
    assert float(m_u8["train_loss"]) == pytest.approx(
        float(m_f32["train_loss"]), rel=1e-5
    )
    assert jnp.isfinite(m_u8["train_loss"])


def test_on_error_substitute_survives_corrupt_records():
    # A corrupt record under on_error="substitute" becomes a zero image
    # and is counted; the good record still decodes. Both decode
    # backends (whichever "auto" resolves to here, plus forced PIL).
    for backend in ("auto", "pil"):
        spec = imagenet_transform_spec(
            crop=64, resize=64, backend=backend, on_error="substitute"
        )
        batch = {
            "content": np.array(
                [_jpeg(80, 70), b"not a jpeg at all"], dtype=object
            ),
            "label_index": np.array([3, 4]),
        }
        out = spec(batch)
        assert out["image"].shape == (2, 64, 64, 3)
        assert np.abs(out["image"][0]).sum() > 0  # good record decoded
        assert np.all(out["image"][1] == 0)  # corrupt -> zero image
        assert spec.substitutions.count == 1, (backend, spec.substitutions)


def test_on_error_raise_is_default():
    spec = imagenet_transform_spec(crop=64, resize=64)
    batch = {
        "content": np.array([b"junk"], dtype=object),
        "label_index": np.array([0]),
    }
    with pytest.raises(Exception):
        spec(batch)
    with pytest.raises(ValueError, match="on_error"):
        imagenet_transform_spec(on_error="skip")


def test_substitute_is_mean_image_in_every_value_space():
    # A substituted record must be the SAME training input regardless of
    # (output_dtype, normalize): zeros post-normalize, the dataset mean
    # raw, round(255*mean) uint8.
    from dss_ml_at_scale_tpu.data.transform import IMAGENET_MEAN

    batch = {
        "content": np.array([b"junk"], dtype=object),
        "label_index": np.array([0]),
    }
    f_norm = imagenet_transform_spec(
        crop=8, resize=8, backend="pil", on_error="substitute"
    )(batch)["image"][0]
    assert np.all(f_norm == 0)
    f_raw = imagenet_transform_spec(
        crop=8, resize=8, backend="pil", normalize=False,
        on_error="substitute",
    )(batch)["image"][0]
    np.testing.assert_allclose(f_raw[0, 0], IMAGENET_MEAN, atol=1e-6)
    u8 = imagenet_transform_spec(
        crop=8, resize=8, backend="pil", output_dtype="uint8",
        on_error="substitute",
    )(batch)["image"][0]
    np.testing.assert_array_equal(
        u8[0, 0], np.round(IMAGENET_MEAN * 255).astype(np.uint8)
    )


def test_shard_batch_specs_rejects_unknown_keys(devices8):
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    from dss_ml_at_scale_tpu.runtime.mesh import shard_batch_to_mesh

    mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
    with pytest.raises(KeyError, match="token"):
        shard_batch_to_mesh(
            {"tokens": np.zeros((2, 8), np.int32)}, mesh,
            specs={"token": P(None, "data")},
        )

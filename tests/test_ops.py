"""Unit tests for the JAX time-series kernels (ops).

Validation strategy per SURVEY.md §4: Kalman/SARIMAX against closed-form
and hand-rolled NumPy filters, the linear filter against scipy, the
optimizer against scipy.optimize — statsmodels itself is not in the
image, so parity is checked against the underlying math.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.optimize
import scipy.signal

from dss_ml_at_scale_tpu.ops import (
    SarimaxConfig,
    arma_generate_sample,
    holt_winters_fit,
    holt_winters_forecast,
    kalman_filter,
    lfilter,
    nelder_mead,
    sarimax_fit,
    sarimax_loglike,
    sarimax_predict,
)


# -- lfilter / ARMA -----------------------------------------------------------


def test_lfilter_matches_scipy(rng):
    b = [1.0, 0.5, 0.2]
    a = [1.0, -0.6, 0.1]
    x = rng.normal(size=300).astype(np.float32)
    ours = np.asarray(lfilter(jnp.array(b), jnp.array(a), jnp.array(x)))
    ref = scipy.signal.lfilter(b, a, x)
    np.testing.assert_allclose(ours, ref, atol=1e-4)


def test_arma_sample_statistics():
    # AR(1) with phi=0.7: lag-1 autocorrelation ~ 0.7 after burn-in.
    s = np.asarray(
        arma_generate_sample(
            jax.random.key(0), jnp.array([1.0, -0.7]), jnp.array([1.0]), 4000, burnin=500
        )
    )
    assert s.shape == (4000,)
    ac = np.corrcoef(s[:-1], s[1:])[0, 1]
    assert abs(ac - 0.7) < 0.05


def test_lfilter_scalar_polynomials():
    # ARMA(0,0): pure white noise through unit polynomials.
    x = np.array([1.0, 2.0, 3.0], np.float32)
    out = np.asarray(lfilter(jnp.array([2.0]), jnp.array([1.0]), jnp.array(x)))
    np.testing.assert_allclose(out, 2.0 * x, atol=1e-6)
    s = arma_generate_sample(jax.random.key(0), jnp.array([1.0]), jnp.array([1.0]), 50)
    assert s.shape == (50,)


def test_arma_sample_vmap_per_sku_keys():
    # The demand generator draws one series per SKU from per-SKU keys
    # (reference: 01-data-generator.py:242-254) — here a single vmap.
    keys = jax.random.split(jax.random.key(1), 5)
    draw = jax.vmap(
        lambda k: arma_generate_sample(
            k, jnp.array([1.0, -0.5]), jnp.array([1.0, 0.3]), 100, burnin=50
        )
    )
    panel = np.asarray(draw(keys))
    assert panel.shape == (5, 100)
    assert len({tuple(np.round(row, 5)) for row in panel}) == 5  # distinct series


# -- Nelder-Mead --------------------------------------------------------------


def test_nelder_mead_rosenbrock_matches_scipy():
    def rosen(v):
        return 100.0 * (v[1] - v[0] ** 2) ** 2 + (1.0 - v[0]) ** 2

    res = nelder_mead(rosen, jnp.array([-1.2, 1.0]), max_iter=500, xatol=1e-6, fatol=1e-9)
    ref = scipy.optimize.minimize(
        lambda v: rosen(jnp.array(v)), [-1.2, 1.0], method="Nelder-Mead"
    )
    np.testing.assert_allclose(np.asarray(res.x), [1.0, 1.0], atol=1e-3)
    assert float(res.fun) <= ref.fun + 1e-6


def test_nelder_mead_vmap_batch():
    centers = jnp.array([[1.0, -2.0], [3.0, 0.5], [-1.0, 4.0]])

    def make_obj(c):
        return lambda v: jnp.sum((v - c) ** 2)

    res = jax.vmap(lambda c: nelder_mead(make_obj(c), jnp.zeros(2), max_iter=300))(centers)
    np.testing.assert_allclose(np.asarray(res.x), np.asarray(centers), atol=1e-3)


def test_nelder_mead_handles_nan_objective():
    # Non-finite regions must not poison the simplex (likelihoods do this).
    def fn(v):
        val = jnp.sum(v**2)
        return jnp.where(v[0] < -0.5, jnp.nan, val)

    res = nelder_mead(fn, jnp.array([1.0, 1.0]), max_iter=300)
    np.testing.assert_allclose(np.asarray(res.x), [0.0, 0.0], atol=1e-3)


# -- Kalman -------------------------------------------------------------------


def _ar1_exact_loglike(y, phi, s2):
    ll = -0.5 * math.log(2 * math.pi * s2 / (1 - phi**2)) - y[0] ** 2 / (
        2 * s2 / (1 - phi**2)
    )
    e = y[1:] - phi * y[:-1]
    ll += np.sum(-0.5 * np.log(2 * math.pi * s2) - e**2 / (2 * s2))
    return ll


def _ar1_series(rng, n, phi=0.7):
    y = np.zeros(n)
    for t in range(1, n):
        y[t] = phi * y[t - 1] + rng.normal()
    return y.astype(np.float32)


def test_kalman_ar1_closed_form(rng):
    phi, s2 = 0.7, 1.0
    y = _ar1_series(rng, 200, phi)
    T = jnp.array([[phi]])
    R = jnp.array([[1.0]])
    Q = jnp.array([[s2]])
    Z = jnp.array([1.0])
    P0 = jnp.array([[s2 / (1 - phi**2)]])
    filt = kalman_filter(jnp.array(y), T, R, Q, Z, 0.0, jnp.zeros(1), P0)
    assert abs(float(filt.loglike) - _ar1_exact_loglike(y, phi, s2)) < 1e-2


def test_kalman_mask_equals_truncation(rng):
    phi = 0.5
    y = _ar1_series(rng, 150, phi)
    T, R, Q, Z = jnp.array([[phi]]), jnp.array([[1.0]]), jnp.array([[1.0]]), jnp.array([1.0])
    P0 = jnp.array([[1.0 / (1 - phi**2)]])
    full = kalman_filter(jnp.array(y[:100]), T, R, Q, Z, 0.0, jnp.zeros(1), P0)
    padded = kalman_filter(
        jnp.array(y), T, R, Q, Z, 0.0, jnp.zeros(1), P0, mask=jnp.arange(150) < 100
    )
    assert abs(float(full.loglike) - float(padded.loglike)) < 1e-3


# -- Holt-Winters -------------------------------------------------------------


def _seasonal_series(rng, n=120, m=4):
    t = np.arange(n)
    return (50 + 0.5 * t + 8 * np.sin(2 * np.pi * t / m) + rng.normal(0, 1, n)).astype(
        np.float32
    )


def test_holt_winters_additive_fit_and_forecast(rng):
    m = 4
    y = _seasonal_series(rng, 120, m)
    res = holt_winters_fit(jnp.array(y), m, seasonal="add")
    assert 0 < float(res.alpha) < 1 and 0 < float(res.gamma) < 1
    fc = np.asarray(holt_winters_forecast(res, 8))
    t = 120 + np.arange(8)
    true = 50 + 0.5 * t + 8 * np.sin(2 * np.pi * t / m)
    assert np.abs(fc - true).max() < 3.0  # within 3 sigma of the noise


def test_holt_winters_damped_mul_boxcox(rng):
    # The reference's fit4 variant: damped additive trend, multiplicative
    # seasonal, Box-Cox (group_apply/02...py:177-185).
    t = np.arange(120)
    y = np.maximum(
        np.exp(0.01 * t) * (10 + 3 * np.sin(2 * np.pi * t / 4)) + rng.normal(0, 0.2, 120),
        0.1,
    ).astype(np.float32)
    res = holt_winters_fit(jnp.array(y), 4, seasonal="mul", damped=True, use_boxcox=True)
    assert 0.8 <= float(res.phi) <= 0.998
    assert abs(float(res.boxcox_lambda)) < 0.5  # exponential data wants lambda ~ 0
    assert np.isfinite(np.asarray(res.fittedvalues)).all()
    fc = np.asarray(holt_winters_forecast(res, 6))
    assert np.isfinite(fc).all() and (fc > 0).all()


def test_holt_winters_short_series_raises():
    with pytest.raises(ValueError, match="2 full seasons"):
        holt_winters_fit(jnp.ones(18), 12)


def test_holt_winters_boxcox_tolerates_zero_demand(rng):
    # Intermittent demand: zero periods must not produce non-finite fits
    # (inputs are clamped to a positive floor, documented deviation).
    y = np.maximum(_seasonal_series(rng, 80), 0)
    y[10] = 0.0
    res = holt_winters_fit(jnp.array(y.astype(np.float32)), 4, use_boxcox=True)
    assert np.isfinite(float(res.sse))
    assert np.isfinite(np.asarray(holt_winters_forecast(res, 4))).all()


def test_holt_winters_vmap(rng):
    ys = jnp.stack([jnp.array(_seasonal_series(rng, 80)) for _ in range(3)])
    res = jax.vmap(lambda y: holt_winters_fit(y, 4, seasonal="add"))(ys)
    assert res.fittedvalues.shape == (3, 80)
    assert np.isfinite(np.asarray(res.sse)).all()


# -- SARIMAX ------------------------------------------------------------------

CFG0 = SarimaxConfig(k_exog=0)


def test_sarimax_loglike_matches_closed_form(rng):
    y = _ar1_series(rng, 300)
    params = np.zeros(CFG0.n_params, np.float32)
    params[0] = 0.7  # phi_1
    ll = float(
        sarimax_loglike(
            CFG0, jnp.array(params), jnp.array(y), jnp.zeros((300, 0)), jnp.array([1, 0, 0]), 300
        )
    )
    assert abs(ll - _ar1_exact_loglike(y, 0.7, 1.0)) < 0.01


@pytest.mark.slow
def test_sarimax_ar1_fit_recovery(rng):
    y = _ar1_series(rng, 300)
    res = sarimax_fit(CFG0, jnp.array(y), jnp.zeros((300, 0)), jnp.array([1, 0, 0]))
    _, phi, _, log_s2 = CFG0.unpack(res.params)
    assert abs(float(phi[0]) - 0.7) < 0.1
    assert np.abs(np.asarray(phi[1:])).max() < 0.05  # masked lags pinned
    assert abs(float(jnp.exp(log_s2)) - 1.0) < 0.2
    # The optimizer must reach at least the likelihood of the true params.
    true = np.zeros(CFG0.n_params, np.float32)
    true[0] = 0.7
    ll_true = float(
        sarimax_loglike(CFG0, jnp.array(true), jnp.array(y), jnp.zeros((300, 0)), jnp.array([1, 0, 0]), 300)
    )
    assert float(res.loglike) >= ll_true - 0.5


@pytest.mark.slow
def test_sarimax_exog_and_difference(rng):
    # y = 5x + random walk: order (0,1,0) with one exog regressor.
    n = 300
    x = rng.normal(size=(n, 1)).astype(np.float32)
    u = np.cumsum(rng.normal(size=n)).astype(np.float32)
    y = 5.0 * x[:, 0] + u
    cfg = SarimaxConfig(k_exog=1)
    res = sarimax_fit(cfg, jnp.array(y), jnp.array(x), jnp.array([0, 1, 0]))
    beta, _, _, _ = cfg.unpack(res.params)
    assert abs(float(beta[0]) - 5.0) < 0.3


@pytest.mark.slow
def test_sarimax_predict_full_range(rng):
    # Train region one-step predictions + dynamic forecast past n_valid,
    # mirroring predict(start=min(train), end=max(score), exog=score_exo).
    n, n_train = 300, 260
    x = rng.normal(size=(n, 1)).astype(np.float32)
    u = np.cumsum(rng.normal(size=n)).astype(np.float32)
    y = 5.0 * x[:, 0] + u
    cfg = SarimaxConfig(k_exog=1)
    res = sarimax_fit(cfg, jnp.array(y), jnp.array(x), jnp.array([0, 1, 0]), n_train)
    pred = np.asarray(
        sarimax_predict(cfg, res.params, jnp.array(y), jnp.array(x), jnp.array([0, 1, 0]), n_train)
    )
    assert pred.shape == (n,)
    # In-sample one-step error ~ innovation scale.
    in_err = np.abs(pred[2:n_train] - y[2:n_train])
    assert np.median(in_err) < 2.0
    # Forecast: exog effect tracked, random walk held at last level.
    fc_err = np.abs(pred[n_train:] - (5.0 * x[n_train:, 0] + u[n_train - 1]))
    assert fc_err.max() < 1.0


@pytest.mark.slow
def test_sarimax_vmap_different_orders_matches_single(rng):
    n = 200
    y1 = _ar1_series(rng, n)
    y2 = np.cumsum(rng.normal(size=n)).astype(np.float32)
    ys = jnp.stack([jnp.array(y1), jnp.array(y2)])
    exogs = jnp.zeros((2, n, 0))
    orders = jnp.array([[1, 0, 0], [0, 1, 1]])
    vres = jax.vmap(lambda y, x, o: sarimax_fit(CFG0, y, x, o))(ys, exogs, orders)
    single = sarimax_fit(CFG0, jnp.array(y1), jnp.zeros((n, 0)), jnp.array([1, 0, 0]))
    np.testing.assert_allclose(
        np.asarray(vres.loglike[0]), float(single.loglike), rtol=1e-4
    )


def test_sarimax_padding_mask(rng):
    # Tail-padded series with n_valid must match the truncated computation —
    # the contract that lets variable-length groups share one vmapped fit.
    y = _ar1_series(rng, 250)
    params = np.zeros(CFG0.n_params, np.float32)
    params[0] = 0.6
    ll_trunc = float(
        sarimax_loglike(CFG0, jnp.array(params), jnp.array(y[:200]), jnp.zeros((200, 0)), jnp.array([1, 0, 0]), 200)
    )
    padded = np.concatenate([y[:200], np.full(50, 1e3, np.float32)])
    ll_pad = float(
        sarimax_loglike(CFG0, jnp.array(params), jnp.array(padded), jnp.zeros((250, 0)), jnp.array([1, 0, 0]), 200)
    )
    assert abs(ll_trunc - ll_pad) < 1e-2

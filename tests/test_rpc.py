"""RPC control plane + multi-host trials executor (§5.8 parity)."""

import subprocess
import sys
import time

import numpy as np
import pytest

from dss_ml_at_scale_tpu.hpo import STATUS_FAIL, STATUS_OK, fmin, hp
from dss_ml_at_scale_tpu.parallel import HostTrials, objective_ref, serve_trial_worker
from dss_ml_at_scale_tpu.parallel.trials import resolve_objective
from dss_ml_at_scale_tpu.runtime import (
    RpcAuthError,
    RpcRemoteError,
    RpcServer,
    rpc_call,
)


# -- transport --------------------------------------------------------------

def test_rpc_roundtrip_and_remote_error():
    server = RpcServer({
        "echo": lambda p: p,
        "boom": lambda p: 1 / 0,
    }).serve_background()
    try:
        addr = f"{server.address[0]}:{server.address[1]}"
        assert rpc_call(addr, "echo", {"x": [1, 2, 3]}) == {"x": [1, 2, 3]}
        assert rpc_call(server.address, "echo", "tuple-addr ok") == "tuple-addr ok"
        with pytest.raises(RpcRemoteError, match="ZeroDivisionError"):
            rpc_call(addr, "boom")
        with pytest.raises(RpcRemoteError, match="KeyError"):
            rpc_call(addr, "no-such-method")
    finally:
        server.shutdown()


def test_rpc_large_payload():
    server = RpcServer({"size": lambda p: len(p)}).serve_background()
    try:
        blob = b"x" * (5 << 20)  # 5 MiB crosses several recv chunks
        assert rpc_call(server.address, "size", blob) == len(blob)
    finally:
        server.shutdown()


def test_rpc_hmac_handshake():
    server = RpcServer(
        {"echo": lambda p: p}, secret=b"team-secret", recv_timeout=2.0
    ).serve_background()
    try:
        # Matching secret: mutual challenge passes, call succeeds.
        assert rpc_call(server.address, "echo", 42, secret=b"team-secret") == 42
        # Wrong secret: server rejects our digest before unpickling anything.
        with pytest.raises((RpcAuthError, ConnectionError)):
            rpc_call(server.address, "echo", 42, secret=b"wrong", timeout=2.0)
        # No secret: the server speaks challenge frames, not pickle — the
        # client chokes on the raw challenge and the request is never
        # dispatched (server read it as a digest and rejected it).
        import pickle as _pickle

        with pytest.raises((ConnectionError, EOFError, OSError,
                            _pickle.UnpicklingError)):
            rpc_call(server.address, "echo", 42, timeout=2.0)
        # Server still healthy for authenticated callers afterwards.
        assert rpc_call(server.address, "echo", "ok", secret="team-secret") == "ok"
    finally:
        server.shutdown()


def test_rpc_refuses_nonloopback_bind_without_secret():
    with pytest.raises(ValueError, match="shared secret"):
        RpcServer({"echo": lambda p: p}, host="0.0.0.0")
    # "" binds INADDR_ANY too — must not slip through as loopback.
    with pytest.raises(ValueError, match="shared secret"):
        RpcServer({"echo": lambda p: p}, host="")
    # An empty secret authenticates nothing; reject it outright.
    with pytest.raises(ValueError, match="non-empty"):
        RpcServer({"echo": lambda p: p}, host="0.0.0.0", secret=b"")
    # Explicit opt-outs both work.
    RpcServer({"echo": lambda p: p}, host="0.0.0.0", secret=b"s").shutdown()
    RpcServer({"echo": lambda p: p}, host="0.0.0.0", allow_insecure=True).shutdown()


def test_rpc_secret_mismatch_fails_fast_with_auth_error():
    # Driver has a secret, worker does not: the client must fail within
    # its short handshake window naming auth, not stall out the full call
    # timeout with an opaque transport error.
    server = RpcServer({"echo": lambda p: p}, recv_timeout=30.0).serve_background()
    try:
        t0 = time.monotonic()
        with pytest.raises(RpcAuthError, match="handshake"):
            rpc_call(server.address, "echo", 1, secret=b"s", timeout=30.0)
        assert time.monotonic() - t0 < 15.0
    finally:
        server.shutdown()


# -- objective references ---------------------------------------------------

def test_objective_ref_roundtrip():
    from dss_ml_at_scale_tpu.hpo import objectives

    ref = objective_ref(objectives.quadratic)
    assert ref == "dss_ml_at_scale_tpu.hpo.objectives:quadratic"
    assert resolve_objective(ref) is objectives.quadratic
    with pytest.raises(ValueError, match="not importable"):
        objective_ref(lambda a: 0.0)


# -- in-process workers -----------------------------------------------------

@pytest.fixture()
def two_workers():
    servers = [serve_trial_worker(block=False) for _ in range(2)]
    yield [f"{s.address[0]}:{s.address[1]}" for s in servers]
    for s in servers:
        s.shutdown()


def test_host_trials_sweep(two_workers):
    trials = HostTrials(two_workers)
    best = fmin(
        "dss_ml_at_scale_tpu.hpo.objectives:quadratic",
        {"x": hp.uniform("x", -10, 10)},
        max_evals=25,
        trials=trials,
        rstate=np.random.default_rng(0),
    )
    assert len(trials.trials) == 25
    assert abs(best["x"] - 3.0) < 2.0  # TPE homes in on the bowl
    assert all(t["result"]["status"] == STATUS_OK for t in trials.trials)


def test_host_trials_failure_isolation(two_workers):
    trials = HostTrials(two_workers)
    best = fmin(
        "dss_ml_at_scale_tpu.hpo.objectives:brittle_quadratic",
        {"x": hp.uniform("x", -10, 10)},
        max_evals=20,
        trials=trials,
        rstate=np.random.default_rng(1),
    )
    statuses = {t["result"]["status"] for t in trials.trials}
    assert statuses == {STATUS_OK, STATUS_FAIL}  # some raised, sweep survived
    assert best["x"] >= 0
    failed = [t for t in trials.trials if t["result"]["status"] == STATUS_FAIL]
    assert all("blew up" in t["result"]["error"] for t in failed)


def test_host_trials_unreachable_worker_retries_onto_live_one(two_workers):
    # One live worker + one dead address: transport failures requeue the
    # trial onto the surviving worker instead of consuming the eval (the
    # PR-3 retry layer), so the sweep completes with every trial ok.
    trials = HostTrials([two_workers[0], "127.0.0.1:1"], rpc_timeout=2.0)
    fmin(
        "dss_ml_at_scale_tpu.hpo.objectives:quadratic",
        {"x": hp.uniform("x", -10, 10)},
        max_evals=10,
        trials=trials,
        rstate=np.random.default_rng(2),
        return_argmin=False,
    )
    assert len(trials.trials) == 10
    assert all(t["result"]["status"] == STATUS_OK for t in trials.trials)


def test_host_trials_transport_retries_exhausted_fail_the_trial(two_workers):
    # With no retries allowed, a trial that lands on the dead address
    # fails permanently — the pre-retry behavior stays reachable.
    trials = HostTrials(
        [two_workers[0], "127.0.0.1:1"], rpc_timeout=2.0, max_retries=0
    )
    fmin(
        "dss_ml_at_scale_tpu.hpo.objectives:quadratic",
        {"x": hp.uniform("x", -10, 10)},
        max_evals=10,
        trials=trials,
        rstate=np.random.default_rng(2),
        return_argmin=False,
    )
    ok = [t for t in trials.trials if t["result"]["status"] == STATUS_OK]
    failed = [t for t in trials.trials if t["result"]["status"] == STATUS_FAIL]
    assert len(ok) + len(failed) == 10 and ok and failed
    assert all("worker" in t["result"]["error"] for t in failed)


def test_host_trials_all_workers_dead_fails_fast():
    # Nothing listens on these ports. Every transport attempt drops its
    # worker; once the live count hits zero the remaining trials must fail
    # immediately rather than each waiting out rpc_timeout in the pool get.
    trials = HostTrials(
        ["127.0.0.1:1", "127.0.0.1:2"], parallelism=2, rpc_timeout=30.0
    )
    t0 = time.monotonic()
    fmin(
        "dss_ml_at_scale_tpu.hpo.objectives:quadratic",
        {"x": hp.uniform("x", -10, 10)},
        max_evals=12,
        trials=trials,
        rstate=np.random.default_rng(4),
        return_argmin=False,
    )
    elapsed = time.monotonic() - t0
    assert len(trials.trials) == 12
    assert all(t["result"]["status"] == STATUS_FAIL for t in trials.trials)
    # 12 trials × 30 s timeout would be 360 s serialized; fail-fast keeps
    # the whole sweep well under one timeout's worth.
    assert elapsed < 25.0, f"sweep stalled {elapsed:.1f}s after pool death"


def test_host_trials_authenticated_worker():
    server = serve_trial_worker(block=False, secret=b"hmac-secret")
    addr = f"{server.address[0]}:{server.address[1]}"
    try:
        trials = HostTrials([addr], secret=b"hmac-secret")
        best = fmin(
            "dss_ml_at_scale_tpu.hpo.objectives:quadratic",
            {"x": hp.uniform("x", -10, 10)},
            max_evals=6,
            trials=trials,
            rstate=np.random.default_rng(5),
        )
        assert all(t["result"]["status"] == STATUS_OK for t in trials.trials)
        assert "x" in best
    finally:
        server.shutdown()


# -- real worker process via the CLI ---------------------------------------

def test_trial_worker_cli_subprocess(tmp_path):
    proc = subprocess.Popen(
        [sys.executable, "-m", "dss_ml_at_scale_tpu.config.cli",
         "trial-worker", "--bind", "127.0.0.1:0"],
        stdout=subprocess.PIPE, text=True,
    )
    try:
        line = proc.stdout.readline()
        addr = line.strip().rsplit(" ", 1)[-1]
        assert rpc_call(addr, "ping", timeout=10.0) == "pong"
        trials = HostTrials([addr])
        best = fmin(
            "dss_ml_at_scale_tpu.hpo.objectives:quadratic",
            {"x": hp.uniform("x", -5, 8)},
            max_evals=8,
            trials=trials,
            rstate=np.random.default_rng(3),
        )
        assert len(trials.trials) == 8
        assert all(t["result"]["status"] == STATUS_OK for t in trials.trials)
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def _broadcast_sweep(n_bytes: int | None, max_evals: int):
    """Two real worker processes, a lasso sweep over the module-level
    ``Broadcast(factory)`` dataset; returns (per-pid results, seconds)."""
    import os
    import time

    env = dict(os.environ)
    if n_bytes is not None:
        env["DSST_BROADCAST_BYTES"] = str(n_bytes)
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "dss_ml_at_scale_tpu.config.cli",
             "trial-worker", "--bind", "127.0.0.1:0"],
            stdout=subprocess.PIPE, text=True, env=env,
        )
        for _ in range(2)
    ]
    try:
        addrs = [p.stdout.readline().strip().rsplit(" ", 1)[-1] for p in procs]
        trials = HostTrials(addrs, parallelism=2)
        t0 = time.perf_counter()
        fmin(
            "dss_ml_at_scale_tpu.hpo.objectives:lasso_broadcast",
            {"alpha": hp.uniform("alpha", 0.01, 2.0)},
            max_evals=max_evals,
            trials=trials,
            rstate=np.random.default_rng(0),
        )
        wall = time.perf_counter() - t0
        results = [t["result"] for t in trials.trials]
        assert all(r["status"] == STATUS_OK for r in results)
        by_pid: dict[int, list[dict]] = {}
        for r in results:
            by_pid.setdefault(r["pid"], []).append(r)
        return by_pid, wall
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            p.wait(timeout=10)


def _assert_materialized_once(by_pid):
    # Trials actually spread across both worker processes...
    assert len(by_pid) == 2, f"expected 2 worker pids, got {by_pid.keys()}"
    # ...and no process ever ran the factory more than once.
    for pid, rs in by_pid.items():
        assert all(r["broadcast_builds"] == 1 for r in rs), (
            f"worker {pid} rebuilt the broadcast: "
            f"{[r['broadcast_builds'] for r in rs]}"
        )


def test_broadcast_materializes_once_per_worker_process(tmp_path):
    """The broadcast shipping regime across a real process boundary
    (``hyperopt/2...py:90-101``): two worker processes, six trials — each
    process builds the module-level ``Broadcast(factory)`` exactly once
    and every trial on that process shares it.  (Sized-down dataset; the
    slow suite runs the same sweep at the real ~100 MB size.)"""
    by_pid, _ = _broadcast_sweep(None, max_evals=6)
    _assert_materialized_once(by_pid)


@pytest.mark.slow
def test_broadcast_regime_at_real_size(tmp_path):
    """The SAME sweep at the reference's actual ~100 MB regime
    (``hyperopt/2...py:90``): materialize-once still holds when the
    dataset is genuinely broadcast-sized, and the wall clock stays in
    build-once territory (two factory builds + cheap per-trial fits,
    not max_evals x 100 MB generations)."""
    by_pid, wall = _broadcast_sweep(100_000_000, max_evals=4)
    _assert_materialized_once(by_pid)
    print(f"~100MB broadcast sweep wall clock: {wall:.1f}s")
    # Generous single-core bound: one 100 MB build per worker plus four
    # lasso fits.  A per-trial rebuild would multiply the build cost by
    # max_evals and blow through this.
    assert wall < 600, f"broadcast sweep took {wall:.0f}s"


def test_fmin_rejects_string_objective_on_local_executors():
    from dss_ml_at_scale_tpu.hpo import Trials

    with pytest.raises(TypeError, match="string ref"):
        fmin(
            "dss_ml_at_scale_tpu.hpo.objectives:quadratic",
            {"x": hp.uniform("x", -1, 1)},
            max_evals=2,
            trials=Trials(),
        )


def test_host_trials_validates_ref_on_driver(two_workers):
    with pytest.raises(ValueError, match="does not resolve"):
        fmin(
            "dss_ml_at_scale_tpu.hpo.objectives:no_such_function",
            {"x": hp.uniform("x", -1, 1)},
            max_evals=2,
            trials=HostTrials(two_workers),
        )

"""Training-health supervisor chaos suite (PR 4).

The property that matters is *deterministic rollback parity*: a run that
hits an injected NaN-gradient step under the health supervisor must
finish with BITWISE-identical parameters to a clean run that simply
never saw the poison batch — the discard select on device is exact, the
skipped step pulls a make-up batch, and the quarantine blocklist makes
the exclusion durable across replay/resume.
"""

import json

import numpy as np
import optax
import pytest

import jax

from dss_ml_at_scale_tpu import telemetry
from dss_ml_at_scale_tpu.hpo import STATUS_FAIL, STATUS_OK, TPE, fmin, hp
from dss_ml_at_scale_tpu.hpo.fmin import Trials
from dss_ml_at_scale_tpu.parallel import ClassifierTask, Trainer, TrainerConfig
from dss_ml_at_scale_tpu.resilience import FaultPlan, QuarantineList, RowRange, faults
from dss_ml_at_scale_tpu.resilience.health import (
    HealthConfig,
    TrainingHealthError,
)
from dss_ml_at_scale_tpu.runtime import make_mesh

from test_models import tiny_resnet
from test_trainer import synthetic_batches


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.clear()


def _counter(name, **labels):
    for m in telemetry.snapshot()["metrics"]:
        if m["name"] == name and (m.get("labels") or {}) == labels:
            return m["value"]
    return 0.0


def _task():
    return ClassifierTask(model=tiny_resnet(num_classes=4), tx=optax.adam(1e-2))


def _fit(batches, health, **cfg):
    trainer = Trainer(
        TrainerConfig(log_every_steps=1000, health=health, **cfg),
        mesh=make_mesh(),
    )
    return trainer.fit(_task(), iter([dict(b) for b in batches]))


def _assert_params_equal(a, b):
    for x, y in zip(
        jax.tree_util.tree_leaves(a.state.params),
        jax.tree_util.tree_leaves(b.state.params),
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# -- fault-plan grammar additions -------------------------------------------

def test_fault_plan_skip_offset_targets_a_specific_hit():
    plan = faults.install(FaultPlan.parse("grads.nonfinite=1@3"))
    fired = [faults.fault_fires("grads.nonfinite") for _ in range(6)]
    assert fired == [False, False, False, True, False, False]
    assert plan.stats()["grads.nonfinite"] == {"hits": 6, "fired": 1}


def test_fault_fires_is_noop_disarmed_and_meters_when_armed():
    faults.clear()
    assert faults.fault_fires("grads.nonfinite") is False
    before = _counter("faults_injected_total", site="loss.spike")
    faults.install(FaultPlan.parse("loss.spike=1"))
    assert faults.fault_fires("loss.spike") is True
    assert _counter("faults_injected_total", site="loss.spike") - before == 1


def test_fault_plan_rejects_bad_skip_offset():
    for bad in ("a=1@-2", "a=1@x", "a=@3"):
        with pytest.raises(ValueError):
            FaultPlan.parse(bad)


# -- the acceptance property: deterministic rollback parity ------------------

def test_rollback_policy_nonfinite_step_matches_clean_run(devices8):
    """grads.nonfinite injected at step 4 under --health-policy rollback:
    the run completes, and final params are bitwise-identical to a clean
    run trained without the poison batch (the skip rung of the ladder:
    on-device discard + make-up batch)."""
    batches = synthetic_batches(10)
    before = _counter("nonfinite_steps_total")

    faults.install(FaultPlan.parse("grads.nonfinite=1@3"))
    poisoned = _fit(
        batches, HealthConfig(policy="rollback"),
        max_epochs=2, steps_per_epoch=4,
    )
    faults.clear()
    clean = _fit(
        [b for i, b in enumerate(batches) if i != 3],
        HealthConfig(policy="rollback"),
        max_epochs=2, steps_per_epoch=4,
    )

    assert int(poisoned.state.step) == 8 == int(clean.state.step)
    assert poisoned.skipped_steps == 1 and poisoned.health_rollbacks == 0
    assert _counter("nonfinite_steps_total") - before == 1
    _assert_params_equal(poisoned, clean)


def test_skip_policy_discards_spike_and_matches_clean_run(devices8):
    """The EWMA z-score detector: a loss spike injected after warmup is
    discarded under policy=skip, with the same clean-run parity."""
    batches = synthetic_batches(10)
    health = HealthConfig(policy="skip", warmup_steps=3, spike_zscore=6.0)
    before = _counter("loss_spikes_total")

    faults.install(FaultPlan.parse("loss.spike=1@5"))
    poisoned = _fit(batches, health, max_epochs=2, steps_per_epoch=4)
    faults.clear()
    clean = _fit(
        [b for i, b in enumerate(batches) if i != 5],
        health, max_epochs=2, steps_per_epoch=4,
    )

    assert int(poisoned.state.step) == 8 == int(clean.state.step)
    assert poisoned.skipped_steps == 1
    assert _counter("loss_spikes_total") - before == 1
    _assert_params_equal(poisoned, clean)


def test_quarantine_records_discarded_batch_provenance(devices8, tmp_path):
    """A discarded batch's provenance lands on the JSONL blocklist (and
    the quarantined_batches_total counter)."""
    q = QuarantineList(tmp_path / "quarantine.jsonl")
    batches = [dict(b) for b in synthetic_batches(6)]
    for i, b in enumerate(batches):
        b["_provenance"] = [RowRange("mem://train", i, 0, 16)]
    before = _counter("quarantined_batches_total")

    faults.install(FaultPlan.parse("grads.nonfinite=1@2"))
    result = _fit(
        batches,
        HealthConfig(policy="skip", quarantine=q),
        max_epochs=1, steps_per_epoch=4,
    )
    assert int(result.state.step) == 4 and result.skipped_steps == 1
    assert _counter("quarantined_batches_total") - before == 1
    assert len(q) == 1
    entry = q.entries[0]
    assert entry["row_group"] == 2 and "nonfinite" in entry["reason"]
    # ...and a fresh QuarantineList reads the same entry back from disk.
    assert len(QuarantineList(tmp_path / "quarantine.jsonl")) == 1


# -- the rollback + abort rungs ---------------------------------------------

def test_rollback_restores_checkpoint_then_aborts_after_budget(
    devices8, tmp_path
):
    """A persistent fault: skip, skip, rollback to the newest intact
    checkpoint, skip, skip, then abort with a diagnostic bundle once
    max_rollbacks is spent."""
    ckpt = tmp_path / "ckpt"
    health = HealthConfig(
        policy="rollback", max_consecutive_skips=1, max_rollbacks=1,
    )
    rb_before = _counter("health_rollbacks_total")
    nf_before = _counter("nonfinite_steps_total")

    faults.install(FaultPlan.parse("grads.nonfinite=100@4"))
    with pytest.raises(TrainingHealthError) as exc_info:
        _fit(
            synthetic_batches(14), health,
            max_epochs=3, steps_per_epoch=2, checkpoint_dir=str(ckpt),
        )

    assert _counter("health_rollbacks_total") - rb_before == 1
    # skip, skip(->rollback), skip, skip(->abort): 4 discarded updates.
    assert _counter("nonfinite_steps_total") - nf_before == 4
    err = exc_info.value
    assert err.bundle_path is not None
    bundle = json.loads((tmp_path / "ckpt" / "health_abort_step5.json").read_text())
    assert bundle["rollbacks"] == 1 and bundle["policy"] == "rollback"
    assert bundle["recent_incidents"][-1]["verdict"] == "nonfinite"
    assert bundle["fault_plan_stats"]["grads.nonfinite"]["fired"] == 4
    # The intact checkpoints survived (steps 2 and 4 from epochs 0/1).
    assert (ckpt / "4").is_dir()


def test_abort_policy_stops_on_first_bad_step(devices8):
    faults.install(FaultPlan.parse("grads.nonfinite=1@1"))
    with pytest.raises(TrainingHealthError):
        _fit(
            synthetic_batches(6), HealthConfig(policy="abort"),
            max_epochs=1, steps_per_epoch=4,
        )


def test_rollback_without_checkpoint_dir_aborts(devices8):
    faults.install(FaultPlan.parse("grads.nonfinite=100"))
    with pytest.raises(TrainingHealthError, match="no checkpoint_dir"):
        _fit(
            synthetic_batches(8),
            HealthConfig(policy="rollback", max_consecutive_skips=1),
            max_epochs=1, steps_per_epoch=4,
        )


def test_health_counters_render_on_metrics_exposition(devices8):
    """The acceptance counters are registered (visible on /metrics and in
    the archived `dsst telemetry` snapshot) as soon as a supervised fit
    runs, even before any incident."""
    _fit(synthetic_batches(4), HealthConfig(policy="skip"),
         max_epochs=1, steps_per_epoch=2)
    text = telemetry.render_prometheus()
    for name in ("nonfinite_steps_total", "loss_spikes_total",
                 "health_rollbacks_total", "quarantined_batches_total"):
        assert name in text


# -- serving satellite: non-finite score guard ------------------------------

def test_serving_score_rejects_nonfinite_probabilities():
    import jax.numpy as jnp

    from dss_ml_at_scale_tpu.workloads.serving import (
        NonFiniteScoreError,
        Predictor,
    )

    p = object.__new__(Predictor)
    p.micro_batch, p.label_names, p.step = 4, None, 7
    p._np, p._jnp = np, jnp
    p._predict_hist = telemetry.histogram("predict_batch_seconds")
    p._predict_images = telemetry.counter("predict_images_total")
    p._predict_errors = telemetry.counter("predict_errors_total")
    p._score = lambda x: (
        jnp.zeros(4, jnp.int32), jnp.full((4,), jnp.nan, jnp.float32)
    )
    before = _counter("scoring_nonfinite_total")
    err_before = _counter("predict_errors_total")
    with pytest.raises(NonFiniteScoreError, match="non-finite"):
        p.score(np.zeros((2, 8, 8, 3), np.float32))
    # Only the 2 REAL rows count — padding rows are garbage by design.
    assert _counter("scoring_nonfinite_total") - before == 2
    assert _counter("predict_errors_total") - err_before == 1

    # Finite scores still flow.
    p._score = lambda x: (
        jnp.zeros(4, jnp.int32), jnp.full((4,), 0.5, jnp.float32)
    )
    assert [r["pred_prob"] for r in p.score(
        np.zeros((2, 8, 8, 3), np.float32)
    )] == [0.5, 0.5]


# -- HPO satellite: non-finite objectives fail their trial -------------------

def test_nonfinite_objective_is_failed_trial_and_best_is_finite():
    space = {"x": hp.uniform("x", 0.0, 10.0)}

    def objective(args):
        # Half the space diverges; the sweep must survive and the winner
        # must come from the finite half.
        return float("nan") if args["x"] < 5.0 else args["x"]

    trials = Trials()
    best = fmin(objective, space, max_evals=20, trials=trials,
                rstate=np.random.default_rng(0))
    statuses = [t["result"]["status"] for t in trials.trials]
    assert STATUS_FAIL in statuses and STATUS_OK in statuses
    failed = [t for t in trials.trials
              if t["result"]["status"] == STATUS_FAIL]
    assert any("non-finite" in t["result"]["error"] for t in failed)
    assert best["x"] >= 5.0
    # The surrogate's history never sees a non-finite loss.
    assert all(np.isfinite(loss) for _, loss in trials._history())


def test_tpe_suggest_ignores_nonfinite_history_entries(rng):
    space = {"x": hp.uniform("x", 0.0, 1.0)}
    # Past startup, with poisoned entries interleaved: NaN would poison
    # the good/bad argsort split without the filter.
    history = [({"x": 0.1 * i}, float(i)) for i in range(8)]
    history += [({"x": 0.5}, float("nan")), ({"x": 0.9}, float("inf"))]
    out = TPE(n_startup_trials=5).suggest(space, history, rng)
    assert 0.0 <= out["x"] <= 1.0

    # All-poison history behaves like a fresh start (startup sampling).
    poison = [({"x": 0.5}, float("nan"))] * 12
    out = TPE(n_startup_trials=5).suggest(space, poison, rng)
    assert 0.0 <= out["x"] <= 1.0


def test_best_trial_skips_nonfinite_loss_recorded_by_foreign_store():
    # A store that bypassed call_with_protocol (custom executor) may have
    # recorded status=ok with a NaN loss; argmin must not crown it.
    trials = Trials()
    trials._record(0, {"x": 1.0}, {"loss": float("nan"), "status": STATUS_OK}, 0.0)
    trials._record(1, {"x": 2.0}, {"loss": 3.0, "status": STATUS_OK}, 0.0)
    assert trials.argmin() == {"x": 2.0}


# -- CLI: dsst quarantine ----------------------------------------------------

def test_cli_quarantine_list_and_clear(tmp_path, capsys):
    from dss_ml_at_scale_tpu.config.cli import main

    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    q = QuarantineList(ckpt / "quarantine.jsonl")
    q.add([RowRange("/data/p.parquet", 3, 16, 32)], reason="test", step=9)

    # A checkpoint dir resolves to its quarantine.jsonl.
    assert main(["quarantine", "list", str(ckpt)]) == 0
    out = capsys.readouterr().out.strip().splitlines()
    entry = json.loads(out[0])
    assert entry["row_group"] == 3 and entry["row_lo"] == 16

    assert main(["quarantine", "clear", str(ckpt)]) == 0
    assert "cleared 1" in capsys.readouterr().out
    assert main(["quarantine", "list", str(ckpt)]) == 1  # nothing left

"""Golden-fixture parity tests for the SARIMAX kernels at HPO-grid orders.

The fixture (``tests/fixtures/sarimax_golden.json``, regenerate with
``python tests/fixtures/gen_sarimax_golden.py``) pins values from an
independent plain-NumPy/SciPy implementation of the same model —
explicit loops, unpadded state dimensions, scipy Lyapunov solve — on an
ARMAX series at EDA scale (~157 weekly points, 3 exogenous regressors,
reference ``group_apply/02_Fine_Grained_Demand_Forecasting.py:226-230``).

Three layers of parity, strongest first:

1. **Likelihood math** — at pinned parameter points the padded/masked
   JAX filter must reproduce the oracle's exact loglike across the
   (p, d, q) grid corners the reference's Hyperopt space visits
   (``02...py:461-469``), including the approximate-diffuse branch.
2. **Prediction math** — full-range predictions (one-step in-sample +
   dynamic beyond) at the same pinned points.
3. **Fit quality** — ``sarimax_fit``'s achieved likelihood vs the
   oracle's best from multi-start f64 Nelder-Mead on the UNPADDED
   parameterization (an easier problem, so a fair bar). Tolerances are
   per-order: tight where the model is well-specified (d >= 1 — the
   demand series is integrated), loose for the misspecified d=0 corner
   whose optimum sits on a unit root with a non-invertible MA, where
   f32 optimization legitimately lands in a different local basin.
"""

import json
from pathlib import Path

import numpy as np
import pytest

import jax.numpy as jnp

from dss_ml_at_scale_tpu.ops import (
    SarimaxConfig,
    sarimax_fit,
    sarimax_loglike,
    sarimax_predict,
)

FIXTURE = Path(__file__).parent / "fixtures" / "sarimax_golden.json"


@pytest.fixture(scope="module")
def golden():
    fix = json.loads(FIXTURE.read_text())
    fix["_y"] = jnp.asarray(fix["y"], jnp.float32)
    fix["_exog"] = jnp.asarray(fix["exog"], jnp.float32)
    return fix


CFG = SarimaxConfig(k_exog=3)


def _pack(case) -> jnp.ndarray:
    return jnp.asarray(
        np.concatenate(
            [
                case["beta"],
                np.pad(case["phi"], (0, CFG.max_p - len(case["phi"]))),
                np.pad(case["theta"], (0, CFG.max_q - len(case["theta"]))),
                [case["log_sigma2"]],
            ]
        ),
        jnp.float32,
    )


def test_loglike_matches_oracle_at_grid_corners(golden):
    for case in golden["cases"]:
        ll = float(
            sarimax_loglike(
                CFG, _pack(case), golden["_y"], golden["_exog"],
                jnp.asarray(case["order"]), golden["n_valid"],
            )
        )
        assert ll == pytest.approx(case["loglike"], rel=1e-4, abs=0.05), (
            f"order {case['order']}: jax {ll} vs oracle {case['loglike']}"
        )


def test_predict_matches_oracle_at_grid_corners(golden):
    for case in golden["cases"]:
        pred = np.asarray(
            sarimax_predict(
                CFG, _pack(case), golden["_y"], golden["_exog"],
                jnp.asarray(case["order"]), golden["n_valid"],
            )
        )
        np.testing.assert_allclose(
            pred, case["predict"], rtol=1e-3, atol=5e-3,
            err_msg=f"order {case['order']}",
        )


# Fit-quality bars: max allowed loglike shortfall vs the oracle's best,
# now across the FULL 5x3x5 grid (75 orders) the HPO searches —
# p<=4, d<=2, q<=4, the reference's own space
# (group_apply/02...py:461-465) — (round-4 verdict:
# corners only left the middle transitively argued).  d >= 1 orders are
# the well-specified ones (the fixture series is integrated) and get a
# complexity-scaled bar; d=0 orders force a stationary model onto an
# integrated series, whose ML optimum sits at a unit root (often with a
# near-cancelling MA) — a basin the f32 3-start NM+BFGS does not
# reliably reach.  It still returns a usable finite fit there, and the
# HPO ranks orders by holdout MSE, not loglike, so the bar is loose but
# bounded.
def _fit_tol(order) -> float:
    p, d, q = order
    if d == 0 and (p or q):
        return 30.0
    return max(1.0, 1.5 * (p + q))


# Calibration record (this host, full 75-order sweep): every d >= 1
# order passes its f32 bar; seven d=0 orders trail by 35-69 nats —
# their ML optimum sits at a unit root with near-cancelling MA, a basin
# the f32 multi-start NM+BFGS does not reliably reach on an integrated
# series.  The production path never stops there: workloads/eda.py
# polishes the winning fit with the host-side float64 NM
# (ops/polish.py) before predicting, and the polish closes every one of
# those orders to <= 1.7 nats (several beat the oracle outright).  The
# test encodes exactly that: f32 bar first, polish escalation for d=0.
POLISH_TOL = 5.0


@pytest.mark.slow
def test_fit_quality_across_full_grid(golden):
    from dss_ml_at_scale_tpu.ops import sarimax_polish

    cfg = SarimaxConfig(k_exog=3, max_iter=600)
    bad = {}
    for bar in golden["fits"]:
        order = tuple(bar["order"])
        res = sarimax_fit(
            cfg, golden["_y"], golden["_exog"], jnp.asarray(bar["order"]),
            golden["n_valid"],
        )
        ll = float(res.loglike)
        assert np.isfinite(ll), f"order {order}: non-finite fit loglike"
        shortfall = bar["loglike"] - ll
        if shortfall <= _fit_tol(order):
            continue
        if order[1] == 0:
            # Unit-root basin: the f64 polish (the EDA production step)
            # must close it.
            _, ll64 = sarimax_polish(
                cfg, res.params, golden["y"], golden["exog"],
                list(order), golden["n_valid"],
            )
            polished = bar["loglike"] - ll64
            if polished <= POLISH_TOL:
                continue
            bad[order] = (round(shortfall, 2),
                          f"polished {round(polished, 2)}")
        else:
            bad[order] = round(shortfall, 2)
    assert not bad, (
        f"orders trailing the oracle beyond tolerance: {bad}"
    )


# ---------------------------------------------------------------------------
# Near-unit-root companion series (d=2-shaped, phi -> 1): the stiffest
# numerical regime the HPO visits — Lyapunov init near singularity,
# likelihood surface near a unit-root ridge (round-4 verdict item 5).
# ---------------------------------------------------------------------------

NUR_CFG = SarimaxConfig(k_exog=2)


def test_nur_loglike_and_predict_match_oracle(golden):
    nur = golden["nur"]
    y = jnp.asarray(nur["y"], jnp.float32)
    exog = jnp.asarray(nur["exog"], jnp.float32)
    for case in nur["cases"]:
        packed = jnp.asarray(
            np.concatenate([
                case["beta"],
                np.pad(case["phi"], (0, NUR_CFG.max_p - len(case["phi"]))),
                np.pad(case["theta"],
                       (0, NUR_CFG.max_q - len(case["theta"]))),
                [case["log_sigma2"]],
            ]),
            jnp.float32,
        )
        ll = float(sarimax_loglike(
            NUR_CFG, packed, y, exog, jnp.asarray(case["order"]),
            nur["n_valid"],
        ))
        assert ll == pytest.approx(case["loglike"], rel=1e-3, abs=0.5), (
            f"nur order {case['order']}: jax {ll} vs oracle "
            f"{case['loglike']}"
        )
        pred = np.asarray(sarimax_predict(
            NUR_CFG, packed, y, exog, jnp.asarray(case["order"]),
            nur["n_valid"],
        ))
        np.testing.assert_allclose(
            pred, case["predict"], rtol=5e-3,
            atol=5e-3 * float(np.max(np.abs(nur["y"]))),
            err_msg=f"nur order {case['order']}",
        )


@pytest.mark.slow
def test_nur_fit_quality(golden):
    nur = golden["nur"]
    y = jnp.asarray(nur["y"], jnp.float32)
    exog = jnp.asarray(nur["exog"], jnp.float32)
    cfg = SarimaxConfig(k_exog=2, max_iter=600)
    shortfalls = {}
    for bar in nur["fits"]:
        order = tuple(bar["order"])
        res = sarimax_fit(
            cfg, y, exog, jnp.asarray(bar["order"]), nur["n_valid"]
        )
        ll = float(res.loglike)
        assert np.isfinite(ll), f"nur order {order}: non-finite loglike"
        shortfalls[order] = bar["loglike"] - ll
    bad = {
        o: round(s, 3) for o, s in shortfalls.items()
        if s > _fit_tol(o) + 2.0  # near-unit-root: extra headroom
    }
    assert not bad, f"nur orders beyond tolerance: {bad}"


@pytest.mark.slow
def test_f64_polish_closes_the_d0_corner(golden):
    """The one corner the f32 fit concedes (FIT_TOL[(4,0,4)] = 25 nats:
    unit-root optimum with near-cancelling MA, too thin for f32) closes
    to oracle precision under the host-side float64 polish
    (``ops/polish.py``) started from the f32 incumbent."""
    from dss_ml_at_scale_tpu.ops import sarimax_polish

    bar = next(b for b in golden["fits"] if tuple(b["order"]) == (4, 0, 4))
    cfg = SarimaxConfig(k_exog=3, max_iter=600)
    res = sarimax_fit(
        cfg, golden["_y"], golden["_exog"], jnp.asarray(bar["order"]),
        golden["n_valid"],
    )
    _, ll64 = sarimax_polish(
        cfg, res.params, golden["y"], golden["exog"], bar["order"],
        golden["n_valid"],
    )
    shortfall = bar["loglike"] - ll64
    assert shortfall <= 3.0, (
        f"polished loglike {ll64:.3f} still trails oracle "
        f"{bar['loglike']:.3f} by {shortfall:.3f}"
    )

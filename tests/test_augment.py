"""On-device RandomResizedCrop + flip (data/augment.py).

Parity context: the reference's Petastorm train transform runs
torchvision RandomResizedCrop + RandomHorizontalFlip on host workers;
here the same augmentation runs inside the jitted train step
(``ClassifierTask(augment=...)``), keyed by ``state.step``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dss_ml_at_scale_tpu.data.augment import (
    AugmentConfig,
    augment_for_step,
    random_resized_crop_flip,
)


def _batch(b=4, h=32, w=32, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).normal(size=(b, h, w, 3)),
        jnp.float32,
    )


def test_output_shape_and_dtype():
    out = random_resized_crop_flip(jax.random.key(0), _batch(), crop=24)
    assert out.shape == (4, 24, 24, 3)
    assert out.dtype == jnp.float32


def test_deterministic_per_step_and_varying_across_steps():
    imgs = _batch()
    a1 = augment_for_step(jnp.int32(7), imgs, 24)
    a2 = augment_for_step(jnp.int32(7), imgs, 24)
    b = augment_for_step(jnp.int32(8), imgs, 24)
    np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))
    assert np.abs(np.asarray(a1) - np.asarray(b)).max() > 1e-3


def test_constant_image_stays_constant():
    # Any crop of a constant field is that constant: catches resampling
    # bugs that mix in out-of-box values (padding, wrap-around).
    imgs = jnp.full((3, 32, 32, 3), 0.625, jnp.float32)
    out = random_resized_crop_flip(jax.random.key(1), imgs, crop=16)
    np.testing.assert_allclose(np.asarray(out), 0.625, rtol=0, atol=1e-5)


def test_each_image_gets_its_own_crop():
    imgs = _batch(b=6)
    out = random_resized_crop_flip(jax.random.key(2), imgs, crop=24)
    flat = np.asarray(out).reshape(6, -1)
    # No two images should be transformed identically.
    for i in range(6):
        for j in range(i + 1, 6):
            assert np.abs(flat[i] - flat[j]).max() > 1e-3


def test_flip_rate_near_half():
    # A horizontal gradient flips sign under mirror: measure the rate.
    ramp = jnp.linspace(-1.0, 1.0, 32)
    imgs = jnp.broadcast_to(ramp[None, None, :, None], (64, 32, 32, 3))
    cfg = AugmentConfig(scale=(0.999, 1.0), ratio=(1.0, 1.0))  # crop≈all
    out = random_resized_crop_flip(
        jax.random.key(3), imgs.astype(jnp.float32), crop=32, cfg=cfg
    )
    # Left-edge mean > right-edge mean => flipped.
    flipped = (
        np.asarray(out)[:, :, :4].mean(axis=(1, 2, 3))
        > np.asarray(out)[:, :, -4:].mean(axis=(1, 2, 3))
    )
    assert 0.25 < flipped.mean() < 0.75


def test_identity_config_recovers_input():
    # scale pinned to 1.0 area and unit ratio, flip off: the sampled box
    # is the whole image and the resample is (numerically) identity.
    imgs = _batch(b=2)
    cfg = AugmentConfig(scale=(1.0, 1.0), ratio=(1.0, 1.0), flip=False)
    out = random_resized_crop_flip(jax.random.key(4), imgs, crop=32, cfg=cfg)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(imgs), rtol=1e-5, atol=1e-5
    )


def color_batches(n_batches, batch=16, seed=0):
    """Crop/flip-INVARIANT labels: class = which channel is bright (3)
    or all-channels-mid (class 3). The quadrant task used elsewhere is
    position-defined, which RandomResizedCrop rightly destroys — an
    augmentation test needs a label the augmentation preserves."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_batches):
        labels = rng.integers(0, 4, batch)
        imgs = rng.normal(0, 0.1, (batch, 32, 32, 3)).astype(np.float32)
        for i, c in enumerate(labels):
            if c < 3:
                imgs[i, :, :, c] += 1.0
            else:
                imgs[i] += 0.5
        out.append({"image": imgs, "label": labels.astype(np.int32)})
    return out


def test_classifier_task_augment_still_learns(devices8):
    """A crop/flip-invariant task learns under full-strength
    augmentation end to end through the DP trainer — proving the
    augment branch compiles under the mesh and preserves the signal."""
    from dss_ml_at_scale_tpu.parallel import (
        ClassifierTask,
        Trainer,
        TrainerConfig,
    )
    from dss_ml_at_scale_tpu.runtime import make_mesh
    from test_models import tiny_resnet

    task = ClassifierTask(
        model=tiny_resnet(num_classes=4),
        tx=optax.adam(1e-2),
        augment=AugmentConfig(),
    )
    trainer = Trainer(
        TrainerConfig(max_epochs=2, steps_per_epoch=20, log_every_steps=1000),
        mesh=make_mesh(),
    )
    result = trainer.fit(
        task,
        iter(color_batches(40)),
        val_data_factory=lambda: color_batches(3, seed=9),
    )
    assert result.history[-1]["train_loss"] < result.history[0]["train_loss"]
    assert result.history[-1]["val_acc"] > 0.5


def test_cli_augment_flag(tmp_path, capsys, devices8):
    """dsst train --augment wires AugmentConfig into the task."""
    import pyarrow as pa

    from test_end_to_end import _jpeg

    from dss_ml_at_scale_tpu.config.cli import main
    from dss_ml_at_scale_tpu.data import write_delta

    rng = np.random.default_rng(0)
    labels = rng.integers(0, 4, 32)
    table = pa.table({
        "content": pa.array([_jpeg(rng, l) for l in labels],
                            type=pa.binary()),
        "label_index": pa.array(labels.astype(np.int64)),
    })
    data = tmp_path / "images"
    write_delta(table, data, max_rows_per_file=16)

    import json

    assert main([
        "train", "--data", str(data), "--model", "tiny",
        "--num-classes", "4", "--crop", "64", "--batch-size", "16",
        "--epochs", "1", "--augment",
    ]) == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["steps"] == 2

"""Test rig: simulate an 8-device TPU slice on host CPU.

The reference has no fake backend; its closest move is single-node
multi-process DDP (SURVEY.md §4.5). The TPU-native analogue is XLA's
host-platform device multiplexing: 8 virtual CPU devices behave like an
8-chip slice for sharding/collective semantics (not performance).

This must run before any test triggers JAX backend init, hence conftest
import time: XLA_FLAGS via env, platform via jax.config (the env var
alone is overridden by preregistered PJRT plugins on some hosts).
"""

import os

_FLAG = "--xla_force_host_platform_device_count=8"
if _FLAG not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") + " " + _FLAG).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# NO persistent compilation cache here, deliberately: this jaxlib's CPU
# backend crashes the whole process (SIGSEGV/SIGABRT, not an exception)
# when it DEserializes a cached executable — the first in-process
# cache hit (e.g. the second fit of a resume test compiling the
# identical train_step) aborts the suite. Compile-time savings are not
# worth a hard crash; re-enable only after verifying
# serialize→deserialize round-trips on the installed jaxlib.

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _isolated_tracking_root(tmp_path, monkeypatch):
    """CLI autologging defaults ON (dsst_runs/ in cwd); redirect every
    test's default root — including subprocess pipelines, which inherit
    the env — under tmp_path so suite runs never litter the repo.
    Tests that pass an explicit --tracking-root are unaffected."""
    monkeypatch.setenv("DSST_TRACKING_ROOT", str(tmp_path / "dsst_runs"))


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 simulated devices, got {len(devs)}"
    return devs


@pytest.fixture()
def rng():
    return np.random.default_rng(0)

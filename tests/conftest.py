"""Test rig: simulate an 8-device TPU slice on host CPU.

The reference has no fake backend; its closest move is single-node
multi-process DDP (SURVEY.md §4.5). The TPU-native analogue is XLA's
host-platform device multiplexing: 8 virtual CPU devices behave like an
8-chip slice for sharding/collective semantics (not performance).

This must run before any test triggers JAX backend init, hence conftest
import time: XLA_FLAGS via env, platform via jax.config (the env var
alone is overridden by preregistered PJRT plugins on some hosts).
"""

import os

_FLAG = "--xla_force_host_platform_device_count=8"
if _FLAG not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") + " " + _FLAG).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# NO persistent compilation cache here, deliberately: this jaxlib's CPU
# backend crashes the whole process (SIGSEGV/SIGABRT, not an exception)
# when it DEserializes a cached executable — the first in-process
# cache hit (e.g. the second fit of a resume test compiling the
# identical train_step) aborts the suite. Compile-time savings are not
# worth a hard crash; re-enable only after verifying
# serialize→deserialize round-trips on the installed jaxlib.

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# DSST_SANITIZE=1 arms the runtime thread sanitizer for the WHOLE
# session: every lock/thread the package creates during the suite is
# instrumented, and unbaselined findings fail the run (exit 1) even
# when every test passed. Opt-in (it adds per-acquire bookkeeping);
# the always-on tier-1 coverage is tests/test_sanitize.py's gate,
# which arms the named workloads inside the normal suite.
if os.environ.get("DSST_SANITIZE"):
    _san_state = {}

    def pytest_configure(config):
        from dss_ml_at_scale_tpu.analysis.sanitize import sanitize_scope

        cm = sanitize_scope()
        _san_state["cm"] = cm
        _san_state["scope"] = cm.__enter__()

    def pytest_sessionfinish(session, exitstatus):
        from dss_ml_at_scale_tpu.analysis.sanitize import build_result

        cm = _san_state.pop("cm", None)
        scope = _san_state.pop("scope", None)
        if cm is None:
            return
        cm.__exit__(None, None, None)
        res = build_result(scope, ["<pytest session>"], full_run=False)
        # The suite deliberately seeds hazards via
        # tests/fixtures/sanitize/ (loaded under the sanfix_ prefix);
        # the session gate judges PACKAGE code, not the fixtures'
        # staged crimes.
        res.findings = [
            f for f in res.findings
            if "tests/fixtures/sanitize/" not in f.path
        ]
        if res.findings:
            print("\n=== dsst sanitize (DSST_SANITIZE=1 session) ===")
            print(res.render_text())
            if session.exitstatus == 0:
                session.exitstatus = 1


@pytest.fixture(autouse=True)
def _isolated_tracking_root(tmp_path, monkeypatch):
    """CLI autologging defaults ON (dsst_runs/ in cwd); redirect every
    test's default root — including subprocess pipelines, which inherit
    the env — under tmp_path so suite runs never litter the repo.
    Tests that pass an explicit --tracking-root are unaffected."""
    monkeypatch.setenv("DSST_TRACKING_ROOT", str(tmp_path / "dsst_runs"))


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 simulated devices, got {len(devs)}"
    return devs


@pytest.fixture()
def rng():
    return np.random.default_rng(0)

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from dss_ml_at_scale_tpu.data import (
    ParquetShardReader,
    TransformSpec,
    batch_loader,
    list_row_groups,
    make_batch_reader,
    shard_units,
    write_delta,
)
from dss_ml_at_scale_tpu.data.transform import Field


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    """8 parquet files × 2 row groups × 16 rows = 256 rows."""
    root = tmp_path_factory.mktemp("ds")
    n = 0
    for f in range(8):
        t = pa.table(
            {
                "id": pa.array(np.arange(n, n + 32)),
                "value": pa.array(np.arange(n, n + 32, dtype=np.float64)),
            }
        )
        pq.write_table(t, root / f"part-{f}.parquet", row_group_size=16)
        n += 32
    return root


def test_list_and_shard_units(dataset):
    units = list_row_groups(sorted(str(p) for p in dataset.glob("*.parquet")))
    assert len(units) == 16
    assert all(u.num_rows == 16 for u in units)
    shards = [shard_units(units, i, 4, epoch=0) for i in range(4)]
    seen = [(u.path, u.row_group) for s in shards for u in s]
    assert len(seen) == 16 and len(set(seen)) == 16  # disjoint cover
    assert all(len(s) == 4 for s in shards)
    # epoch varies the permutation but shard 0 of every process agrees
    again = shard_units(units, 0, 4, epoch=0)
    assert [(u.path, u.row_group) for u in again] == [
        (u.path, u.row_group) for u in shards[0]
    ]
    other_epoch = shard_units(units, 0, 4, epoch=1)
    assert [(u.path, u.row_group) for u in other_epoch] != [
        (u.path, u.row_group) for u in shards[0]
    ]


def test_queue_occupancy_tracks_results_queue(dataset):
    reader = ParquetShardReader(
        sorted(str(p) for p in dataset.glob("*.parquet")),
        batch_size=16, num_epochs=1, results_queue_size=4,
    )
    assert reader.queue_occupancy == 0  # not iterating yet
    it = iter(reader)
    next(it)
    # Workers run ahead of a stalled consumer up to the queue bound.
    import time

    deadline = time.monotonic() + 2.0
    while reader.queue_occupancy < 4 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert 0 < reader.queue_occupancy <= 4
    reader.stop()


def test_single_epoch_reads_all_rows(dataset):
    with batch_loader(
        dataset, batch_size=32, num_epochs=1, workers_count=3, shuffle_row_groups=False
    ) as reader:
        ids = np.concatenate([b["id"] for b in reader])
    assert sorted(ids.tolist()) == list(range(256))


def test_batches_are_fixed_shape_and_drop_last(dataset):
    with batch_loader(dataset, batch_size=48, num_epochs=1) as reader:
        batches = list(reader)
    # 256 // 48 = 5 full batches; remainder 16 dropped
    assert len(batches) == 5
    assert all(len(b["id"]) == 48 for b in batches)


def test_keep_last_partial_batch(dataset):
    with batch_loader(dataset, batch_size=48, num_epochs=1, drop_last=False) as reader:
        batches = list(reader)
    assert [len(b["id"]) for b in batches] == [48] * 5 + [16]


def test_sharded_readers_are_disjoint(dataset):
    all_ids = []
    for shard in range(4):
        with batch_loader(
            dataset, batch_size=16, num_epochs=1, cur_shard=shard, shard_count=4
        ) as reader:
            all_ids += [b["id"] for b in reader]
    flat = np.concatenate(all_ids)
    assert sorted(flat.tolist()) == list(range(256))


def test_infinite_reader_crosses_epochs(dataset):
    with batch_loader(dataset, batch_size=100, num_epochs=None) as reader:
        it = iter(reader)
        got = sum(len(next(it)["id"]) for _ in range(5))
    assert got == 500  # > one 256-row epoch: reader kept going


def test_transform_spec_applied(dataset):
    spec = TransformSpec(
        func=lambda cols: {"twice": cols["value"] * 2},
        fields=[Field("twice", np.dtype(np.float32), ())],
    )
    with batch_loader(
        dataset, batch_size=64, num_epochs=1, transform_spec=spec, shuffle_row_groups=False
    ) as reader:
        b = next(iter(reader))
    assert set(b) == {"twice"}
    assert b["twice"].dtype == np.float32


def test_transform_spec_validates_schema(dataset):
    bad = TransformSpec(
        func=lambda cols: {"wrong_name": cols["value"]},
        fields=[Field("twice", np.dtype(np.float32), ())],
    )
    with pytest.raises(ValueError, match="declared"):
        with batch_loader(
            dataset, batch_size=8, num_epochs=1, transform_spec=bad,
            reader_pool_type="dummy",
        ) as reader:
            next(iter(reader))


def test_reader_from_delta_table(dataset, tmp_path):
    t = pa.table({"id": pa.array(np.arange(64))})
    write_delta(t, tmp_path / "dt", max_rows_per_file=16)
    with batch_loader(tmp_path / "dt", batch_size=16, num_epochs=1) as reader:
        ids = np.concatenate([b["id"] for b in reader])
    assert sorted(ids.tolist()) == list(range(64))


def test_too_many_shards_raises(dataset):
    with pytest.raises(ValueError, match="row groups"):
        ParquetShardReader(
            sorted(str(p) for p in dataset.glob("*.parquet")),
            batch_size=4,
            shard_count=64,
        )


def test_memory_estimate(dataset):
    reader = make_batch_reader(
        dataset, batch_size=4, workers_count=2, results_queue_size=20, num_epochs=1
    )
    # (2 workers + 20 queue slots) × 16 rows/group × 100 B
    assert reader.memory_estimate(row_size_bytes=100) == 22 * 16 * 100


def test_stop_unblocks_workers_quickly(dataset):
    reader = make_batch_reader(
        dataset, batch_size=8, num_epochs=None, workers_count=4, results_queue_size=2
    )
    it = iter(reader)
    next(it)  # spin up workers, queue fills
    reader.stop()
    assert all(not t.is_alive() for t in reader._threads)


def test_worker_exception_propagates_in_thread_pool(dataset):
    """A failing transform must raise, not end the stream silently."""
    from dss_ml_at_scale_tpu.data.transform import Field

    def boom(cols):
        raise OSError("decode failed")

    bad = TransformSpec(func=boom, fields=[Field("x", np.dtype(np.float32), ())])
    with pytest.raises(RuntimeError, match="worker failed"):
        with batch_loader(
            dataset, batch_size=8, num_epochs=None, transform_spec=bad,
            reader_pool_type="thread", workers_count=2,
        ) as reader:
            next(iter(reader))


# -- provenance, quarantine, corrupt-sample isolation (PR 4) -----------------

def _sorted_files(dataset):
    return sorted(str(p) for p in dataset.glob("*.parquet"))


def test_emit_provenance_tags_batches_with_exact_rows(dataset):
    from dss_ml_at_scale_tpu.resilience.rollback import PROVENANCE_KEY

    with batch_loader(
        _sorted_files(dataset), batch_size=24, num_epochs=1,
        shuffle_row_groups=False, reader_pool_type="dummy",
        emit_provenance=True,
    ) as reader:
        batches = list(reader)
    for b in batches:
        prov = b[PROVENANCE_KEY]
        assert sum(r.num_rows for r in prov) == len(b["id"])
    # File order + dummy pool: batch 0 is rows [0,16) of rg0 + [0,8) of
    # rg1 of the first file — provenance must say exactly that.
    first = batches[0][PROVENANCE_KEY]
    assert [(r.row_group, r.row_lo, r.row_hi) for r in first] == [
        (0, 0, 16), (1, 0, 8),
    ]


def test_quarantined_rows_are_excluded_exactly(dataset, tmp_path):
    """Reader-level exclusion repacks the surviving stream: the batches
    equal a trainer-side skip of the same rows — the mechanism behind
    deterministic rollback parity."""
    from dss_ml_at_scale_tpu.resilience.rollback import (
        PROVENANCE_KEY,
        QuarantineList,
    )

    kwargs = dict(
        batch_size=16, num_epochs=1, shuffle_row_groups=False,
        reader_pool_type="dummy",
    )
    with batch_loader(
        _sorted_files(dataset), emit_provenance=True, **kwargs
    ) as reader:
        batches = list(reader)
    poison = batches[2]
    q = QuarantineList(tmp_path / "q.jsonl")
    q.add(poison[PROVENANCE_KEY], reason="chaos", step=3)

    with batch_loader(
        _sorted_files(dataset), quarantine=q, **kwargs
    ) as reader:
        excluded = [b["id"] for b in reader]
    skipped = [b["id"] for i, b in enumerate(batches) if i != 2]
    assert len(excluded) == len(skipped)
    for a, b in zip(excluded, skipped):
        np.testing.assert_array_equal(a, b)


def test_corrupt_sample_quarantined_and_skipped(dataset, tmp_path):
    """on_corrupt="quarantine": a row whose transform raises is isolated,
    counted, blocklisted, and dropped — the reader thread survives."""
    from dss_ml_at_scale_tpu import telemetry
    from dss_ml_at_scale_tpu.data.transform import Field
    from dss_ml_at_scale_tpu.resilience.rollback import QuarantineList

    def decode(cols):
        if np.any(cols["id"] == 100):
            raise ValueError("bad row")
        return {"value": cols["value"].astype(np.float32)}

    spec = TransformSpec(
        func=decode, fields=[Field("value", np.dtype(np.float32), ())]
    )

    def counter_value():
        for m in telemetry.snapshot()["metrics"]:
            if m["name"] == "corrupt_samples_total":
                return m["value"]
        return 0.0

    before = counter_value()
    q = QuarantineList(tmp_path / "q.jsonl")
    with batch_loader(
        _sorted_files(dataset), batch_size=16, num_epochs=1,
        shuffle_row_groups=False, transform_spec=spec, workers_count=2,
        quarantine=q, on_corrupt="quarantine", drop_last=False,
    ) as reader:
        values = np.concatenate([b["value"] for b in reader])
    assert len(values) == 255  # row id=100 dropped
    assert 100.0 not in values
    assert counter_value() - before == 1
    assert len(q) == 1
    entry = q.entries[0]
    assert entry["row_hi"] - entry["row_lo"] == 1
    assert "undecodable" in entry["reason"]

    # Default on_corrupt="raise" preserves fail-fast semantics.
    with pytest.raises(RuntimeError, match="worker failed"):
        with batch_loader(
            _sorted_files(dataset), batch_size=16, num_epochs=1,
            transform_spec=spec, workers_count=2,
        ) as reader:
            list(reader)


def test_sample_corrupt_fault_site_truncates_bytes(tmp_path):
    """The sample.corrupt site: truncated payload bytes hit the real
    decode error path and end up quarantined, deterministically."""
    from dss_ml_at_scale_tpu.data.transform import Field
    from dss_ml_at_scale_tpu.resilience import FaultPlan, faults
    from dss_ml_at_scale_tpu.resilience.rollback import QuarantineList

    t = pa.table({
        "payload": pa.array([np.float64(i).tobytes() for i in range(32)],
                            type=pa.binary()),
    })
    path = tmp_path / "bytes.parquet"
    pq.write_table(t, path, row_group_size=16)

    spec = TransformSpec(
        func=lambda cols: {"value": np.array(
            [np.frombuffer(b, np.float64, count=1)[0] for b in cols["payload"]],
            np.float64,
        )},
        fields=[Field("value", np.dtype(np.float64), ())],
    )
    q = QuarantineList(tmp_path / "q.jsonl")
    faults.install(FaultPlan.parse("sample.corrupt=1"))
    try:
        with batch_loader(
            [str(path)], batch_size=16, num_epochs=1, drop_last=False,
            shuffle_row_groups=False, reader_pool_type="dummy",
            transform_spec=spec, quarantine=q, on_corrupt="quarantine",
        ) as reader:
            values = np.concatenate([b["value"] for b in reader])
    finally:
        faults.clear()
    # Row 0 of the first row group was truncated mid-payload and dropped.
    assert len(values) == 31 and 0.0 not in values
    assert len(q) == 1 and q.entries[0]["row_lo"] == 0

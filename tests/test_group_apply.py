"""Tests for the group-apply engine and the forecasting workload."""

import numpy as np
import pandas as pd
import pytest

import jax
import jax.numpy as jnp

from dss_ml_at_scale_tpu.hpo import Trials, fmin, hp
from dss_ml_at_scale_tpu.ops import (
    SarimaxConfig,
    grid_orders,
    sarimax_fit,
    sarimax_fit_grid,
    sarimax_loglike,
)
from dss_ml_at_scale_tpu.parallel.group_apply import (
    batched_fmin,
    device_put_groups,
    grid_fit_panel,
    group_apply,
    pad_groups,
    pad_to_multiple,
    shard_of,
)
from dss_ml_at_scale_tpu.runtime import make_mesh
from dss_ml_at_scale_tpu.workloads import (
    add_exo_variables,
    split_train_score_data,
    tune_and_forecast_panel,
)


def _demand_frame(rng, n_sku=4, weeks=60):
    dates = pd.date_range("2019-06-03", periods=weeks, freq="W-MON")
    rows = []
    for s in range(n_sku):
        base = 100 + 10 * s
        demand = base + 0.4 * np.arange(weeks) + rng.normal(0, 3, weeks)
        rows.append(
            pd.DataFrame(
                {
                    "Date": dates,
                    "Product": f"P{s % 2}",
                    "SKU": f"SKU{s}",
                    "Demand": demand.astype(np.float32),
                }
            )
        )
    return pd.concat(rows, ignore_index=True)


# -- host path ----------------------------------------------------------------


def test_group_apply_concat(rng):
    df = _demand_frame(rng)

    def summarize(g):
        return pd.DataFrame(
            {"SKU": [g["SKU"].iloc[0]], "mean": [g["Demand"].mean()]}
        )

    out = group_apply(df, "SKU", summarize)
    assert sorted(out["SKU"]) == [f"SKU{i}" for i in range(4)]
    assert np.isfinite(out["mean"]).all()


def test_group_apply_multihost_shards_partition(rng):
    df = _demand_frame(rng, n_sku=7)
    fn = lambda g: g.head(1)[["Product", "SKU"]]
    parts = [
        group_apply(df, ["Product", "SKU"], fn, process_index=i, process_count=3)
        for i in range(3)
    ]
    union = pd.concat([p for p in parts if len(p)], ignore_index=True)
    assert len(union) == 7  # disjoint and complete
    assert set(union["SKU"]) == set(df["SKU"])
    # Deterministic assignment: same hash every call.
    assert shard_of(("P0", "SKU0"), 3) == shard_of(("P0", "SKU0"), 3)


def test_group_apply_failure_isolation(rng):
    df = _demand_frame(rng)

    def fn(g):
        if g["SKU"].iloc[0] == "SKU2":
            raise RuntimeError("boom")
        return g.head(1)[["SKU"]]

    with pytest.raises(RuntimeError):
        group_apply(df, "SKU", fn)
    out = group_apply(df, "SKU", fn, on_error="skip")
    assert set(out["SKU"]) == {"SKU0", "SKU1", "SKU3"}


@pytest.mark.slow
def test_group_apply_process_executor(rng):
    # GIL-bound per-group fns get real process isolation (the reference's
    # execution shape: one Python worker process per Spark task). The fn
    # ships by module reference; results must match the thread path and
    # run in worker processes, not this one.
    import os

    from dss_ml_at_scale_tpu.hpo.objectives import group_pid_summary

    df = _demand_frame(rng)
    out = group_apply(
        df, "SKU", group_pid_summary, executor="process", num_workers=2
    )
    assert sorted(out["SKU"]) == [f"SKU{i}" for i in range(4)]
    expected = df.groupby("SKU")["Demand"].mean()
    for _, row in out.iterrows():
        np.testing.assert_allclose(row["mean"], expected[row["SKU"]], rtol=1e-6)
    assert (out["pid"] != os.getpid()).all(), "groups ran in-process"


@pytest.mark.slow
def test_group_apply_process_executor_failure_isolation(rng):
    from dss_ml_at_scale_tpu.hpo.objectives import brittle_group_head

    df = _demand_frame(rng)
    with pytest.raises(RuntimeError, match="group blew up"):
        group_apply(df, "SKU", brittle_group_head, executor="process")
    out = group_apply(
        df, "SKU", brittle_group_head, executor="process", on_error="skip"
    )
    assert set(out["SKU"]) == {"SKU0", "SKU1", "SKU3"}


def test_group_apply_process_executor_rejects_closures(rng):
    df = _demand_frame(rng)
    with pytest.raises(ValueError, match="not importable"):
        group_apply(df, "SKU", lambda g: g, executor="process")
    with pytest.raises(ValueError, match="executor"):
        group_apply(df, "SKU", lambda g: g, executor="bogus")


# -- padding / device placement ----------------------------------------------


def test_pad_groups_ragged():
    df = pd.DataFrame(
        {
            "k": ["a"] * 3 + ["b"] * 5,
            "t": [2, 0, 1] + [4, 3, 2, 1, 0],
            "v": [2.0, 0.0, 1.0, 14.0, 13.0, 12.0, 11.0, 10.0],
        }
    )
    padded = pad_groups(df, "k", ["v"], sort_by="t")
    assert padded.values["v"].shape == (2, 5)
    np.testing.assert_array_equal(padded.n_valid, [3, 5])
    np.testing.assert_allclose(padded.values["v"][0], [0, 1, 2, 0, 0])
    np.testing.assert_allclose(padded.values["v"][1], [10, 11, 12, 13, 14])
    assert list(padded.keys["k"]) == ["a", "b"]


def test_pad_groups_stable_within_group_order():
    # Duplicate sort keys must keep frame order (stable lexsort): the
    # vectorized scatter cannot reorder ties the way an unstable
    # per-group quicksort could.
    df = pd.DataFrame(
        {
            "k": ["a", "a", "a", "b", "b"],
            "t": [1, 0, 1, 2, 2],
            "v": [10.0, 20.0, 30.0, 40.0, 50.0],
        }
    )
    padded = pad_groups(df, "k", ["v"], sort_by="t")
    np.testing.assert_allclose(padded.values["v"][0], [20, 10, 30])
    np.testing.assert_allclose(padded.values["v"][1, :2], [40, 50])
    # No sort_by: rows keep frame order within each group.
    padded2 = pad_groups(df, "k", ["v"])
    np.testing.assert_allclose(padded2.values["v"][0], [10, 20, 30])


def test_pad_groups_drops_null_key_rows():
    # groupby drops null-key groups; the vectorized scatter must mirror
    # that (not crash on the NaN ngroup codes those rows produce).
    df = pd.DataFrame(
        {
            "k": ["a", None, "b", "a"],
            "v": [1.0, 99.0, 3.0, 2.0],
        }
    )
    padded = pad_groups(df, "k", ["v"])
    assert padded.n_groups == 2
    np.testing.assert_allclose(padded.values["v"][0], [1, 2])
    np.testing.assert_allclose(padded.values["v"][1], [3, 0])
    assert list(padded.keys["k"]) == ["a", "b"]


def test_pad_to_multiple_and_mesh_sharding(devices8):
    mesh = make_mesh({"data": 8})
    arr = np.arange(5 * 4, dtype=np.float32).reshape(5, 4)
    out = device_put_groups(arr, mesh)
    assert out.shape == (8, 4)  # padded 5 -> 8
    assert len(out.sharding.device_set) == 8
    np.testing.assert_allclose(np.asarray(out)[:5], arr)
    assert pad_to_multiple(arr, 5).shape == (5, 4)  # no-op when divisible


# -- batched nested HPO -------------------------------------------------------


def test_batched_fmin_matches_sequential_fmin():
    # One group, deterministic objective: the batched driver must replay
    # the exact proposal stream of the sequential fmin (same TPE, same rng).
    space = {"x": hp.uniform("x", 0, 10)}
    obj = lambda p: (p["x"] - 3.0) ** 2

    trials = Trials()
    fmin(obj, space, max_evals=12, trials=trials, rstate=7)
    seq_points = [t["point"]["x"] for t in trials.trials]

    best, hist = batched_fmin(
        lambda pts: np.array([obj(pts[0])]), space, 12, 1,
        rstate=[np.random.default_rng(7)],
    )
    batch_points = [p["x"] for p, _ in hist[0]]
    np.testing.assert_allclose(batch_points, seq_points, rtol=1e-12)
    assert abs(best[0]["x"] - 3.0) < 1.0


def test_batched_fmin_independent_groups():
    # Different per-group optima; every group must find its own.
    targets = np.array([1.0, 5.0, 8.0])
    space = {"x": hp.uniform("x", 0, 10)}

    def evaluate(points):
        xs = np.array([p["x"] for p in points])
        return (xs - targets) ** 2

    best, hist = batched_fmin(evaluate, space, 25, 3, rstate=np.random.default_rng(0))
    found = np.array([b["x"] for b in best])
    np.testing.assert_allclose(found, targets, atol=1.2)
    # Intermittent non-finite losses are dropped per group, not fatal.
    calls = {"n": 0}

    def eval_nan(points):
        out = (np.array([p["x"] for p in points]) - targets) ** 2
        if calls["n"] < 2:
            out[1] = np.nan
        calls["n"] += 1
        return out

    _, hist2 = batched_fmin(eval_nan, space, 5, 3, rstate=0)
    assert len(hist2[1]) == 3  # 2 failed rounds excluded
    assert np.isfinite([l for _, l in hist2[1]]).all()
    # An all-failing group raises, mirroring fmin's "no successful trials".
    with pytest.raises(ValueError, match="no successful trials"):
        batched_fmin(
            lambda pts: np.full(3, np.nan), space, 2, 3, rstate=0
        )


# -- grid-fused engine --------------------------------------------------------

# Tiny exog-free config: K = 4 orders, short NM chains — grid-engine
# mechanics (argmin, chunking, sharding) without golden-grade fit cost.
TINY_CFG = SarimaxConfig(
    max_p=1, max_d=0, max_q=1, k_exog=0, max_iter=12, bfgs_iter=0
)


def _series_panel(rng, G=6, L=24, holdout=6):
    y = (50 + np.cumsum(rng.normal(0, 1, (G, L)), axis=1)).astype(np.float32)
    exog = np.zeros((G, L, 0), np.float32)
    n_valid = np.full(G, L, np.int32)
    n_train = np.full(G, L - holdout, np.int32)
    return y, exog, n_train, n_valid


def test_grid_fit_device_argmin_matches_host_argmin(rng):
    # The on-device reduction must agree with fitting each order
    # separately and reducing on the host: same kernel, same winner.
    y, exog, n_train, n_valid = _series_panel(rng, G=1)
    orders = grid_orders(TINY_CFG)
    assert orders.shape == (4, 3)  # 2 x 1 x 2 at the tiny bounds
    res = sarimax_fit_grid(
        TINY_CFG, y[0], exog[0], orders, n_train[0], n_valid[0],
        select="loglike",
    )
    per_order = [
        float(sarimax_fit(TINY_CFG, y[0], exog[0], o, n_train[0]).loglike)
        for o in orders
    ]
    # Tolerance: the vmapped fit plane and a single-lane fit are
    # different compiled programs; f32 NM can settle a few hundredths
    # of a nat apart without the winner changing.
    assert float(res.loglike) >= max(per_order) - 0.05
    assert float(res.loglike) == pytest.approx(max(per_order), abs=0.05)
    # The winner's loglike is the exact (unconcentrated) likelihood at
    # the returned params.
    ll = float(sarimax_loglike(
        TINY_CFG, res.params, y[0], exog[0], res.order, n_train[0]
    ))
    assert ll == pytest.approx(float(res.loglike), abs=1e-3)


def test_grid_fit_panel_chunking_invariant(rng):
    # The chunked launch family must reproduce the single-launch result
    # exactly: padding lanes are discarded work, never visible output.
    from dss_ml_at_scale_tpu import telemetry

    def fitted_total():
        for m in telemetry.snapshot()["metrics"]:
            if m["name"] == "skus_fitted_total":
                return m["value"]
        return 0.0

    y, exog, n_train, n_valid = _series_panel(rng, G=10)
    fitted0 = fitted_total()
    full = grid_fit_panel(TINY_CFG, y, exog, n_train, n_valid)
    chunked = grid_fit_panel(
        TINY_CFG, y, exog, n_train, n_valid, chunk_size=4
    )
    assert full.chunks == 1 and chunked.chunks == 3
    np.testing.assert_array_equal(full.order, chunked.order)
    np.testing.assert_allclose(full.pred, chunked.pred, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        full.loglike, chunked.loglike, rtol=1e-5, atol=1e-4
    )
    assert full.pred.shape == y.shape
    # 10 real groups per call; pad lanes are never counted as fitted.
    assert fitted_total() - fitted0 == 20


def test_grid_beats_tpe_on_holdout(rng):
    # The tentpole claim at workload level: the exact grid argmin is
    # never worse than TPE sampling of the same space, per group, on
    # the reference's own tuning objective (holdout MSE).
    cfg = SarimaxConfig(
        max_p=1, max_d=1, max_q=1, k_exog=3, max_iter=20, bfgs_iter=0
    )
    df = add_exo_variables(_demand_frame(rng, n_sku=3, weeks=40))
    kwargs = dict(forecast_horizon=8, cfg=cfg)
    grid = tune_and_forecast_panel(df, **kwargs)
    tpe = tune_and_forecast_panel(df, max_evals=3, search="tpe", **kwargs)
    assert len(grid) == len(df)
    for sku, g in grid.groupby("SKU"):
        t = tpe[tpe["SKU"] == sku]
        hold_g = g.tail(8)
        hold_t = t.tail(8)
        mse_g = float(np.mean(
            (hold_g["Demand"].to_numpy() - hold_g["Demand_Fitted"].to_numpy()) ** 2
        ))
        mse_t = float(np.mean(
            (hold_t["Demand"].to_numpy() - hold_t["Demand_Fitted"].to_numpy()) ** 2
        ))
        assert mse_g <= mse_t + 1e-2, (sku, mse_g, mse_t)


def test_tune_and_forecast_panel_rejects_unknown_search(rng):
    df = add_exo_variables(_demand_frame(rng, n_sku=1, weeks=20))
    with pytest.raises(ValueError, match="search"):
        tune_and_forecast_panel(df, search="bogus")


def test_tune_and_forecast_panel_drops_null_key_rows(rng):
    # pad_groups drops null-key rows (groupby semantics); reassembly
    # must work from the same filtered row set, not crash on a length
    # mismatch. The launch-count side channel rides the output frame.
    cfg = SarimaxConfig(
        max_p=0, max_d=0, max_q=0, k_exog=3, max_iter=5, bfgs_iter=0
    )
    df = add_exo_variables(_demand_frame(rng, n_sku=2, weeks=20))
    df.loc[3, "SKU"] = None
    out = tune_and_forecast_panel(df, forecast_horizon=5, cfg=cfg)
    assert len(out) == len(df) - 1
    assert np.isfinite(out["Demand_Fitted"]).all()
    assert out.attrs["grid_chunks"] == 1
    assert out.attrs["groups_fitted"] == 2


def test_axis_name_threads_through_nondata_mesh(rng, devices8):
    # Satellite regression: a mesh whose group axis is NOT named "data"
    # must work on both paths — put_orders used to hardcode "data" and
    # mis-shard (crash) the TPE path's orders.
    mesh = make_mesh({"groups": 8})
    cfg = SarimaxConfig(
        max_p=0, max_d=0, max_q=0, k_exog=3, max_iter=5, bfgs_iter=0
    )
    df = add_exo_variables(_demand_frame(rng, n_sku=2, weeks=20))
    for search in ("grid", "tpe"):
        out = tune_and_forecast_panel(
            df, max_evals=1, forecast_horizon=5, cfg=cfg, mesh=mesh,
            axis_name="groups", search=search,
        )
        assert len(out) == len(df), search
        assert np.isfinite(out["Demand_Fitted"]).all(), search


@pytest.mark.slow
def test_grid_parity_on_golden_fixture():
    # Acceptance gate: on the golden fixture series, the grid-fused
    # path's best loglike is >= the per-round batched_fmin path's best
    # (same fit kernel, same search space; exact argmin vs 10 TPE
    # samples at the reference's rstate).
    import json
    from pathlib import Path

    from dss_ml_at_scale_tpu.workloads import SEARCH_SPACE

    fix = json.loads(
        (Path(__file__).parent / "fixtures" / "sarimax_golden.json")
        .read_text()
    )
    y = np.asarray(fix["y"], np.float32)
    exog = np.asarray(fix["exog"], np.float32)
    n_valid = int(fix["n_valid"])
    cfg = SarimaxConfig(k_exog=3, max_iter=100, bfgs_iter=0)
    orders = grid_orders(cfg)
    assert orders.shape == (75, 3)  # the full reference grid

    res = sarimax_fit_grid(
        cfg, y, exog, orders, n_valid, n_valid, select="loglike"
    )

    def evaluate(points):
        o = np.array(
            [[points[0]["p"], points[0]["d"], points[0]["q"]]], np.int32
        )
        ll = float(sarimax_fit(cfg, y, exog, o[0], n_valid).loglike)
        return np.array([-ll])

    _, hist = batched_fmin(evaluate, SEARCH_SPACE, 10, 1, rstate=123)
    tpe_best_ll = -min(loss for _, loss in hist[0])
    assert float(res.loglike) >= tpe_best_ll - 1e-2, (
        f"grid {float(res.loglike)} vs tpe {tpe_best_ll}"
    )


@pytest.mark.slow
def test_grid_host_path_matches_device_path(rng):
    # applyInPandas-style host path (one grid-fused 1-group panel per
    # group) vs the batched device path: same fits, same forecasts.
    from dss_ml_at_scale_tpu.workloads import build_tune_and_score_model

    cfg = SarimaxConfig(
        max_p=1, max_d=1, max_q=1, k_exog=3, max_iter=20, bfgs_iter=0
    )
    df = add_exo_variables(_demand_frame(rng, n_sku=3, weeks=36))
    device = tune_and_forecast_panel(df, forecast_horizon=8, cfg=cfg)
    host = group_apply(
        df, ["Product", "SKU"],
        lambda g: build_tune_and_score_model(
            g, forecast_horizon=8, cfg=cfg
        ),
        executor="inline",
    )
    key = ["Product", "SKU", "Date"]
    device = device.sort_values(key).reset_index(drop=True)
    host = host.sort_values(key).reset_index(drop=True)
    pd.testing.assert_frame_equal(device[key], host[key])
    np.testing.assert_allclose(
        device["Demand_Fitted"], host["Demand_Fitted"],
        rtol=1e-4, atol=1e-3,
    )


@pytest.mark.slow
def test_grid_fit_panel_10k_chunked_smoke(rng, devices8):
    # ROADMAP item 3 scale shape: 10k groups through the bounded chunked
    # launch family, sharded over the mesh — no host loop, no per-group
    # Python, finite output for every group.
    cfg = SarimaxConfig(
        max_p=1, max_d=0, max_q=0, k_exog=0, max_iter=8, bfgs_iter=0
    )
    G, L = 10_000, 16
    y = (20 + np.cumsum(rng.normal(0, 1, (G, L)), axis=1)).astype(np.float32)
    exog = np.zeros((G, L, 0), np.float32)
    n_train = np.full(G, L - 4, np.int32)
    n_valid = np.full(G, L, np.int32)
    mesh = make_mesh({"data": 8})
    res = grid_fit_panel(
        cfg, y, exog, n_train, n_valid, mesh=mesh, chunk_size=2048
    )
    assert res.chunks == 5
    assert res.pred.shape == (G, L)
    assert res.order.shape == (G, 3)
    assert np.isfinite(res.loglike).all()
    assert np.isfinite(res.pred).all()


# -- forecasting workload -----------------------------------------------------

CFG_SMALL = SarimaxConfig(max_p=2, max_d=1, max_q=2, k_exog=3, max_iter=60)


def test_add_exo_variables_flags():
    dates = pd.to_datetime(["2019-12-23", "2020-01-13", "2020-03-02", "2019-07-01"])
    df = pd.DataFrame(
        {"Date": dates, "Product": "P", "SKU": "S", "Demand": [1.0, 2.0, 3.0, 4.0]}
    )
    out = add_exo_variables(df)
    np.testing.assert_array_equal(out["covid"], [0, 0, 1, 0])  # breakpoint 2020-03-01
    np.testing.assert_array_equal(out["christmas"], [1, 0, 0, 0])  # ISO weeks 51-52
    np.testing.assert_array_equal(out["new_year"], [0, 1, 0, 0])  # ISO weeks 1-4
    assert list(out.columns) == ["Date", "Product", "SKU", "Demand", "covid", "christmas", "new_year"]


def test_split_train_score():
    df = pd.DataFrame({"x": range(100)})
    train, score = split_train_score_data(df, 40)
    assert len(train) == 60 and len(score) == 40
    assert score["x"].iloc[0] == 60


@pytest.mark.slow
def test_tune_and_forecast_panel(rng):
    df = add_exo_variables(_demand_frame(rng, n_sku=3, weeks=60))
    out = tune_and_forecast_panel(
        df, max_evals=3, forecast_horizon=12, cfg=CFG_SMALL
    )
    assert list(out.columns) == ["Product", "SKU", "Date", "Demand", "Demand_Fitted"]
    assert len(out) == len(df)
    assert np.isfinite(out["Demand_Fitted"]).all()
    # Holdout forecasts must track the trend within a loose band.
    last = out.groupby("SKU").tail(12)
    mape = np.abs(last["Demand_Fitted"] - last["Demand"]) / last["Demand"]
    assert mape.median() < 0.25


@pytest.mark.slow
def test_tune_and_forecast_panel_hundreds_of_groups(rng, devices8):
    # Reference scale contract ("thousands of SKUs", group_apply/02...py:
    # 516-528): G in the hundreds through the sharded vmapped tuner on the
    # simulated mesh. Correctness anchor: with a scalar rstate every group
    # runs an identical, independent TPE stream (reference seeds every SKU
    # with rstate=123), so any SKU re-tuned alone must reproduce its
    # panel-run fit exactly — batch size and mesh placement cannot leak
    # into a group's result.
    G, weeks, horizon = 200, 32, 8
    mesh = make_mesh({"data": 8})
    cfg = SarimaxConfig(max_p=1, max_d=1, max_q=1, k_exog=3, max_iter=30)
    df = add_exo_variables(_demand_frame(rng, n_sku=G, weeks=weeks))
    kwargs = dict(max_evals=2, forecast_horizon=horizon, cfg=cfg, rstate=123)
    out = tune_and_forecast_panel(df, mesh=mesh, **kwargs)
    assert len(out) == len(df)
    assert out["SKU"].nunique() == G
    assert np.isfinite(out["Demand_Fitted"]).all()

    pick = ["SKU0", "SKU57", "SKU199"]
    sub = df[df["SKU"].isin(pick)].reset_index(drop=True)
    sub_out = tune_and_forecast_panel(sub, **kwargs)
    merged = out[out["SKU"].isin(pick)].reset_index(drop=True)
    for sku in pick:
        np.testing.assert_allclose(
            merged[merged["SKU"] == sku]["Demand_Fitted"].to_numpy(),
            sub_out[sub_out["SKU"] == sku]["Demand_Fitted"].to_numpy(),
            rtol=1e-4, atol=1e-3, err_msg=sku,
        )


@pytest.mark.slow
def test_tune_and_forecast_panel_mesh_matches_unsharded(rng, devices8):
    # The flagship group-parallel claim (reference contract
    # group_apply/02...py:516-528, one task per group): G >> n_devices
    # groups sharded over the mesh must produce the same forecasts as the
    # unsharded path — same TPE stream, same fits, different placement.
    mesh = make_mesh({"data": 8})
    df = add_exo_variables(_demand_frame(rng, n_sku=12, weeks=48))
    kwargs = dict(max_evals=2, forecast_horizon=10, cfg=CFG_SMALL, rstate=123)
    sharded = tune_and_forecast_panel(df, mesh=mesh, **kwargs)
    unsharded = tune_and_forecast_panel(df, **kwargs)
    assert len(sharded) == len(df)
    assert np.isfinite(sharded["Demand_Fitted"]).all()
    pd.testing.assert_frame_equal(
        sharded[["Product", "SKU", "Date"]], unsharded[["Product", "SKU", "Date"]]
    )
    np.testing.assert_allclose(
        sharded["Demand_Fitted"], unsharded["Demand_Fitted"], rtol=1e-4, atol=1e-3
    )

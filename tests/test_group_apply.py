"""Tests for the group-apply engine and the forecasting workload."""

import numpy as np
import pandas as pd
import pytest

import jax
import jax.numpy as jnp

from dss_ml_at_scale_tpu.hpo import Trials, fmin, hp
from dss_ml_at_scale_tpu.ops import SarimaxConfig
from dss_ml_at_scale_tpu.parallel.group_apply import (
    batched_fmin,
    device_put_groups,
    group_apply,
    pad_groups,
    pad_to_multiple,
    shard_of,
)
from dss_ml_at_scale_tpu.runtime import make_mesh
from dss_ml_at_scale_tpu.workloads import (
    add_exo_variables,
    split_train_score_data,
    tune_and_forecast_panel,
)


def _demand_frame(rng, n_sku=4, weeks=60):
    dates = pd.date_range("2019-06-03", periods=weeks, freq="W-MON")
    rows = []
    for s in range(n_sku):
        base = 100 + 10 * s
        demand = base + 0.4 * np.arange(weeks) + rng.normal(0, 3, weeks)
        rows.append(
            pd.DataFrame(
                {
                    "Date": dates,
                    "Product": f"P{s % 2}",
                    "SKU": f"SKU{s}",
                    "Demand": demand.astype(np.float32),
                }
            )
        )
    return pd.concat(rows, ignore_index=True)


# -- host path ----------------------------------------------------------------


def test_group_apply_concat(rng):
    df = _demand_frame(rng)

    def summarize(g):
        return pd.DataFrame(
            {"SKU": [g["SKU"].iloc[0]], "mean": [g["Demand"].mean()]}
        )

    out = group_apply(df, "SKU", summarize)
    assert sorted(out["SKU"]) == [f"SKU{i}" for i in range(4)]
    assert np.isfinite(out["mean"]).all()


def test_group_apply_multihost_shards_partition(rng):
    df = _demand_frame(rng, n_sku=7)
    fn = lambda g: g.head(1)[["Product", "SKU"]]
    parts = [
        group_apply(df, ["Product", "SKU"], fn, process_index=i, process_count=3)
        for i in range(3)
    ]
    union = pd.concat([p for p in parts if len(p)], ignore_index=True)
    assert len(union) == 7  # disjoint and complete
    assert set(union["SKU"]) == set(df["SKU"])
    # Deterministic assignment: same hash every call.
    assert shard_of(("P0", "SKU0"), 3) == shard_of(("P0", "SKU0"), 3)


def test_group_apply_failure_isolation(rng):
    df = _demand_frame(rng)

    def fn(g):
        if g["SKU"].iloc[0] == "SKU2":
            raise RuntimeError("boom")
        return g.head(1)[["SKU"]]

    with pytest.raises(RuntimeError):
        group_apply(df, "SKU", fn)
    out = group_apply(df, "SKU", fn, on_error="skip")
    assert set(out["SKU"]) == {"SKU0", "SKU1", "SKU3"}


@pytest.mark.slow
def test_group_apply_process_executor(rng):
    # GIL-bound per-group fns get real process isolation (the reference's
    # execution shape: one Python worker process per Spark task). The fn
    # ships by module reference; results must match the thread path and
    # run in worker processes, not this one.
    import os

    from dss_ml_at_scale_tpu.hpo.objectives import group_pid_summary

    df = _demand_frame(rng)
    out = group_apply(
        df, "SKU", group_pid_summary, executor="process", num_workers=2
    )
    assert sorted(out["SKU"]) == [f"SKU{i}" for i in range(4)]
    expected = df.groupby("SKU")["Demand"].mean()
    for _, row in out.iterrows():
        np.testing.assert_allclose(row["mean"], expected[row["SKU"]], rtol=1e-6)
    assert (out["pid"] != os.getpid()).all(), "groups ran in-process"


@pytest.mark.slow
def test_group_apply_process_executor_failure_isolation(rng):
    from dss_ml_at_scale_tpu.hpo.objectives import brittle_group_head

    df = _demand_frame(rng)
    with pytest.raises(RuntimeError, match="group blew up"):
        group_apply(df, "SKU", brittle_group_head, executor="process")
    out = group_apply(
        df, "SKU", brittle_group_head, executor="process", on_error="skip"
    )
    assert set(out["SKU"]) == {"SKU0", "SKU1", "SKU3"}


def test_group_apply_process_executor_rejects_closures(rng):
    df = _demand_frame(rng)
    with pytest.raises(ValueError, match="not importable"):
        group_apply(df, "SKU", lambda g: g, executor="process")
    with pytest.raises(ValueError, match="executor"):
        group_apply(df, "SKU", lambda g: g, executor="bogus")


# -- padding / device placement ----------------------------------------------


def test_pad_groups_ragged():
    df = pd.DataFrame(
        {
            "k": ["a"] * 3 + ["b"] * 5,
            "t": [2, 0, 1] + [4, 3, 2, 1, 0],
            "v": [2.0, 0.0, 1.0, 14.0, 13.0, 12.0, 11.0, 10.0],
        }
    )
    padded = pad_groups(df, "k", ["v"], sort_by="t")
    assert padded.values["v"].shape == (2, 5)
    np.testing.assert_array_equal(padded.n_valid, [3, 5])
    np.testing.assert_allclose(padded.values["v"][0], [0, 1, 2, 0, 0])
    np.testing.assert_allclose(padded.values["v"][1], [10, 11, 12, 13, 14])
    assert list(padded.keys["k"]) == ["a", "b"]


def test_pad_to_multiple_and_mesh_sharding(devices8):
    mesh = make_mesh({"data": 8})
    arr = np.arange(5 * 4, dtype=np.float32).reshape(5, 4)
    out = device_put_groups(arr, mesh)
    assert out.shape == (8, 4)  # padded 5 -> 8
    assert len(out.sharding.device_set) == 8
    np.testing.assert_allclose(np.asarray(out)[:5], arr)
    assert pad_to_multiple(arr, 5).shape == (5, 4)  # no-op when divisible


# -- batched nested HPO -------------------------------------------------------


def test_batched_fmin_matches_sequential_fmin():
    # One group, deterministic objective: the batched driver must replay
    # the exact proposal stream of the sequential fmin (same TPE, same rng).
    space = {"x": hp.uniform("x", 0, 10)}
    obj = lambda p: (p["x"] - 3.0) ** 2

    trials = Trials()
    fmin(obj, space, max_evals=12, trials=trials, rstate=7)
    seq_points = [t["point"]["x"] for t in trials.trials]

    best, hist = batched_fmin(
        lambda pts: np.array([obj(pts[0])]), space, 12, 1,
        rstate=[np.random.default_rng(7)],
    )
    batch_points = [p["x"] for p, _ in hist[0]]
    np.testing.assert_allclose(batch_points, seq_points, rtol=1e-12)
    assert abs(best[0]["x"] - 3.0) < 1.0


def test_batched_fmin_independent_groups():
    # Different per-group optima; every group must find its own.
    targets = np.array([1.0, 5.0, 8.0])
    space = {"x": hp.uniform("x", 0, 10)}

    def evaluate(points):
        xs = np.array([p["x"] for p in points])
        return (xs - targets) ** 2

    best, hist = batched_fmin(evaluate, space, 25, 3, rstate=np.random.default_rng(0))
    found = np.array([b["x"] for b in best])
    np.testing.assert_allclose(found, targets, atol=1.2)
    # Intermittent non-finite losses are dropped per group, not fatal.
    calls = {"n": 0}

    def eval_nan(points):
        out = (np.array([p["x"] for p in points]) - targets) ** 2
        if calls["n"] < 2:
            out[1] = np.nan
        calls["n"] += 1
        return out

    _, hist2 = batched_fmin(eval_nan, space, 5, 3, rstate=0)
    assert len(hist2[1]) == 3  # 2 failed rounds excluded
    assert np.isfinite([l for _, l in hist2[1]]).all()
    # An all-failing group raises, mirroring fmin's "no successful trials".
    with pytest.raises(ValueError, match="no successful trials"):
        batched_fmin(
            lambda pts: np.full(3, np.nan), space, 2, 3, rstate=0
        )


# -- forecasting workload -----------------------------------------------------

CFG_SMALL = SarimaxConfig(max_p=2, max_d=1, max_q=2, k_exog=3, max_iter=60)


def test_add_exo_variables_flags():
    dates = pd.to_datetime(["2019-12-23", "2020-01-13", "2020-03-02", "2019-07-01"])
    df = pd.DataFrame(
        {"Date": dates, "Product": "P", "SKU": "S", "Demand": [1.0, 2.0, 3.0, 4.0]}
    )
    out = add_exo_variables(df)
    np.testing.assert_array_equal(out["covid"], [0, 0, 1, 0])  # breakpoint 2020-03-01
    np.testing.assert_array_equal(out["christmas"], [1, 0, 0, 0])  # ISO weeks 51-52
    np.testing.assert_array_equal(out["new_year"], [0, 1, 0, 0])  # ISO weeks 1-4
    assert list(out.columns) == ["Date", "Product", "SKU", "Demand", "covid", "christmas", "new_year"]


def test_split_train_score():
    df = pd.DataFrame({"x": range(100)})
    train, score = split_train_score_data(df, 40)
    assert len(train) == 60 and len(score) == 40
    assert score["x"].iloc[0] == 60


@pytest.mark.slow
def test_tune_and_forecast_panel(rng):
    df = add_exo_variables(_demand_frame(rng, n_sku=3, weeks=60))
    out = tune_and_forecast_panel(
        df, max_evals=3, forecast_horizon=12, cfg=CFG_SMALL
    )
    assert list(out.columns) == ["Product", "SKU", "Date", "Demand", "Demand_Fitted"]
    assert len(out) == len(df)
    assert np.isfinite(out["Demand_Fitted"]).all()
    # Holdout forecasts must track the trend within a loose band.
    last = out.groupby("SKU").tail(12)
    mape = np.abs(last["Demand_Fitted"] - last["Demand"]) / last["Demand"]
    assert mape.median() < 0.25


@pytest.mark.slow
def test_tune_and_forecast_panel_hundreds_of_groups(rng, devices8):
    # Reference scale contract ("thousands of SKUs", group_apply/02...py:
    # 516-528): G in the hundreds through the sharded vmapped tuner on the
    # simulated mesh. Correctness anchor: with a scalar rstate every group
    # runs an identical, independent TPE stream (reference seeds every SKU
    # with rstate=123), so any SKU re-tuned alone must reproduce its
    # panel-run fit exactly — batch size and mesh placement cannot leak
    # into a group's result.
    G, weeks, horizon = 200, 32, 8
    mesh = make_mesh({"data": 8})
    cfg = SarimaxConfig(max_p=1, max_d=1, max_q=1, k_exog=3, max_iter=30)
    df = add_exo_variables(_demand_frame(rng, n_sku=G, weeks=weeks))
    kwargs = dict(max_evals=2, forecast_horizon=horizon, cfg=cfg, rstate=123)
    out = tune_and_forecast_panel(df, mesh=mesh, **kwargs)
    assert len(out) == len(df)
    assert out["SKU"].nunique() == G
    assert np.isfinite(out["Demand_Fitted"]).all()

    pick = ["SKU0", "SKU57", "SKU199"]
    sub = df[df["SKU"].isin(pick)].reset_index(drop=True)
    sub_out = tune_and_forecast_panel(sub, **kwargs)
    merged = out[out["SKU"].isin(pick)].reset_index(drop=True)
    for sku in pick:
        np.testing.assert_allclose(
            merged[merged["SKU"] == sku]["Demand_Fitted"].to_numpy(),
            sub_out[sub_out["SKU"] == sku]["Demand_Fitted"].to_numpy(),
            rtol=1e-4, atol=1e-3, err_msg=sku,
        )


@pytest.mark.slow
def test_tune_and_forecast_panel_mesh_matches_unsharded(rng, devices8):
    # The flagship group-parallel claim (reference contract
    # group_apply/02...py:516-528, one task per group): G >> n_devices
    # groups sharded over the mesh must produce the same forecasts as the
    # unsharded path — same TPE stream, same fits, different placement.
    mesh = make_mesh({"data": 8})
    df = add_exo_variables(_demand_frame(rng, n_sku=12, weeks=48))
    kwargs = dict(max_evals=2, forecast_horizon=10, cfg=CFG_SMALL, rstate=123)
    sharded = tune_and_forecast_panel(df, mesh=mesh, **kwargs)
    unsharded = tune_and_forecast_panel(df, **kwargs)
    assert len(sharded) == len(df)
    assert np.isfinite(sharded["Demand_Fitted"]).all()
    pd.testing.assert_frame_equal(
        sharded[["Product", "SKU", "Date"]], unsharded[["Product", "SKU", "Date"]]
    )
    np.testing.assert_allclose(
        sharded["Demand_Fitted"], unsharded["Demand_Fitted"], rtol=1e-4, atol=1e-3
    )

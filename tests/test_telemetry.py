"""Unified telemetry subsystem (dss_ml_at_scale_tpu/telemetry/).

Registry math and concurrency, Prometheus/JSON renderers, span log +
Perfetto export, device monitor degradation on CPU, compile tracking,
Trainer wiring, the serving `/metrics` scrape, run archival, the
`dsst telemetry` CLI, and the <50 µs/step instrumentation budget.
"""

import http.client
import json
import threading
import time

import numpy as np
import pytest

from dss_ml_at_scale_tpu import telemetry
from dss_ml_at_scale_tpu.telemetry import (
    CompileTracker,
    DeviceMonitor,
    MetricsRegistry,
    SpanLog,
    export_perfetto,
    log_buckets,
    to_perfetto,
)


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    """Zero the process-default registry and span log around each test so
    cross-test counts never leak into assertions."""
    telemetry.reset()
    yield
    telemetry.reset()


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_counter_gauge_basics():
    r = MetricsRegistry()
    c = r.counter("requests", "total requests")
    c.inc()
    c.inc(4)
    g = r.gauge("depth")
    g.set(7)
    g.inc(3)
    g.dec(5)
    snap = {m["name"]: m for m in r.snapshot()["metrics"]}
    assert snap["requests"]["value"] == 5.0
    assert snap["depth"]["value"] == 5.0
    with pytest.raises(ValueError):
        c.inc(-1)  # counters only go up


def test_get_or_create_identity_and_kind_mismatch():
    r = MetricsRegistry()
    assert r.counter("x") is r.counter("x")
    with pytest.raises(ValueError):
        r.gauge("x")  # same name, different kind
    r.counter("labeled", labels=("a",))
    with pytest.raises(ValueError):
        r.counter("labeled", labels=("b",))  # label-schema fork
    r.histogram("h", buckets=(0.1, 1.0))
    with pytest.raises(ValueError):
        r.histogram("h", buckets=(5.0, 50.0))  # bucket-schema fork


def test_log_bucket_edges():
    edges = log_buckets(1e-6, 100.0, per_decade=3)
    assert edges[0] == 1e-6 and edges[-1] == 100.0
    assert len(edges) == 25  # 8 decades x 3 + 1
    assert all(a < b for a, b in zip(edges, edges[1:]))  # strictly rising
    # Log spacing: constant ratio between consecutive edges.
    ratios = [b / a for a, b in zip(edges, edges[1:])]
    assert max(ratios) / min(ratios) < 1.01


def test_histogram_bucket_edges_le_semantics():
    r = MetricsRegistry()
    h = r.histogram("lat", buckets=(0.001, 0.01, 0.1))
    for v in (0.0005, 0.001, 0.005, 0.05, 5.0):
        h.observe(v)
    (m,) = r.snapshot()["metrics"]
    assert m["count"] == 5
    assert m["sum"] == pytest.approx(5.0565)
    # Cumulative le counts: 0.001 catches 0.0005 AND the exact edge.
    assert m["buckets"] == [
        ["0.001", 2], ["0.01", 3], ["0.1", 4], ["+Inf", 5],
    ]


def test_counter_concurrency_under_threads():
    r = MetricsRegistry()
    c = r.counter("hits")
    h = r.histogram("obs", buckets=(1.0,))
    n_threads, per_thread = 8, 10_000

    def work():
        for _ in range(per_thread):
            c.inc()
            h.observe(0.5)

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = {m["name"]: m for m in r.snapshot()["metrics"]}
    assert snap["hits"]["value"] == n_threads * per_thread
    assert snap["obs"]["count"] == n_threads * per_thread


def test_prometheus_rendering_types_and_escaping():
    r = MetricsRegistry()
    r.counter("total", "all of\nthem").inc(2)
    r.histogram("lat", "latency", labels=("path",), buckets=(0.1, 1.0)) \
        .labels(path='/a"b\\c\nd').observe(0.05)
    text = r.render_prometheus()
    assert "# TYPE total counter" in text
    assert "# HELP total all of\\nthem" in text
    assert "# TYPE lat histogram" in text
    # Label escaping: quote, backslash, newline.
    assert 'path="/a\\"b\\\\c\\nd"' in text
    assert 'lat_bucket{path="/a\\"b\\\\c\\nd",le="0.1"} 1' in text
    assert 'lat_bucket{path="/a\\"b\\\\c\\nd",le="+Inf"} 1' in text
    assert "lat_count" in text and "lat_sum" in text
    assert "total 2" in text


def test_registry_reset_keeps_registrations():
    r = MetricsRegistry()
    c = r.counter("n")
    c.inc(3)
    r.reset()
    snap = {m["name"]: m for m in r.snapshot()["metrics"]}
    assert snap["n"]["value"] == 0.0
    c.inc()  # same family object still live
    assert r.snapshot()["metrics"][0]["value"] == 1.0


# ---------------------------------------------------------------------------
# spans / Perfetto
# ---------------------------------------------------------------------------

def test_span_log_and_perfetto_roundtrip(tmp_path):
    log = SpanLog()
    with log.span("outer", epoch=0):
        with log.span("inner"):
            time.sleep(0.002)
    events = log.events()
    assert [e["name"] for e in events] == ["inner", "outer"]  # close order
    assert events[1]["dur"] >= events[0]["dur"] >= 0.002
    assert "args" not in events[0]  # no-arg spans stay lean
    assert events[1]["args"] == {"epoch": 0}

    # JSONL -> Chrome trace_event file round trip.
    jsonl = tmp_path / "spans.jsonl"
    assert log.dump_jsonl(jsonl) == 2
    out = tmp_path / "trace.json"
    assert export_perfetto(jsonl, out) == 2
    trace = json.loads(out.read_text())  # valid JSON by construction
    assert trace["displayTimeUnit"] == "ms"
    evs = trace["traceEvents"]
    # ph "M" metadata labels the lanes (process + this thread's name);
    # the spans themselves are ph "X" complete events.
    meta = [e for e in evs if e["ph"] == "M"]
    assert {e["name"] for e in meta} == {"process_name", "thread_name"}
    assert any(
        e["name"] == "thread_name" and e["args"]["name"] for e in meta
    )
    xs = [e for e in evs if e["ph"] == "X"]
    assert len(xs) == 2
    for e in xs:
        assert e["dur"] >= 0
    # Monotonic microsecond timestamps (metadata first at ts 0).
    ts = [e["ts"] for e in evs]
    assert ts == sorted(ts)


def test_span_log_capacity_bounded():
    log = SpanLog(capacity=10)
    for i in range(50):
        log.record(f"e{i}", float(i), 0.1)
    events = log.events()
    assert len(events) == 10
    assert events[0]["name"] == "e40"  # oldest evicted


def test_to_perfetto_sorts_unordered_events():
    events = [
        {"name": "b", "ts": 2.0, "dur": 0.1},
        {"name": "a", "ts": 1.0, "dur": 0.1},
    ]
    trace = to_perfetto(events)
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert [e["name"] for e in xs] == ["a", "b"]


# ---------------------------------------------------------------------------
# device telemetry
# ---------------------------------------------------------------------------

def test_device_monitor_degrades_on_cpu(devices8):
    r = MetricsRegistry()
    mon = DeviceMonitor(r, devices=devices8)
    mon.sample()  # must not raise: CPU memory_stats may be None
    snap = {
        (m["name"], m["labels"].get("device")): m
        for m in r.snapshot()["metrics"]
    }
    # Every device reported its supportedness; samples counted.
    supported = [
        m for (name, _), m in snap.items()
        if name == "device_memory_stats_supported"
    ]
    assert len(supported) == 8
    assert snap[("device_monitor_samples_total", None)]["value"] == 1.0
    # Background thread start/stop is clean.
    mon.interval_s = 0.01
    mon.start()
    time.sleep(0.05)
    mon.stop()


def test_compile_tracker_counts_retraces():
    import jax
    import jax.numpy as jnp

    r = MetricsRegistry()
    counter = r.counter("compiles")
    fn = jax.jit(lambda x: x * 2)
    tracker = CompileTracker(fn, counter)
    fn(1.0)
    assert tracker.update() == 1  # first call compiled
    fn(2.0)
    assert tracker.update() == 0  # cache hit
    fn(jnp.zeros((4,)))
    assert tracker.update() == 1  # new shape -> retrace
    assert r.snapshot()["metrics"][0]["value"] == 2.0


# ---------------------------------------------------------------------------
# trainer wiring
# ---------------------------------------------------------------------------

def test_trainer_fit_records_metric_series_and_spans(devices8):
    import optax

    from test_models import tiny_resnet
    from test_trainer import synthetic_batches

    from dss_ml_at_scale_tpu.parallel import (
        ClassifierTask,
        Trainer,
        TrainerConfig,
    )
    from dss_ml_at_scale_tpu.runtime import make_mesh

    task = ClassifierTask(model=tiny_resnet(num_classes=4),
                          tx=optax.adam(1e-2))
    trainer = Trainer(
        TrainerConfig(max_epochs=1, steps_per_epoch=8,
                      log_every_steps=1000),
        mesh=make_mesh(),
    )
    result = trainer.fit(task, iter(synthetic_batches(8)))
    assert len(result.history) == 1

    snap = {m["name"]: m for m in telemetry.snapshot()["metrics"]}
    # >= 4 distinct series: step time, data wait, throughput, compiles.
    # The per-step histograms are SAMPLED 1-in-4 (exact totals ride the
    # feeder counters): 8 ticks -> 7 intervals, compile skipped -> 6
    # recorded -> 1 sampled; 8 waits -> 2 sampled.
    assert snap["train_step_seconds"]["count"] == 6 // 4
    assert snap["train_data_wait_seconds"]["count"] == 8 // 4
    assert snap["train_throughput_rows_per_sec"]["value"] > 0
    assert snap["train_compile_events_total"]["value"] >= 1
    # The feeder staged + sharded every batch on its own thread, with
    # exact batch/stall accounting and occupancy/depth gauges.
    train_feeder = {
        m["name"]: m
        for m in telemetry.snapshot()["metrics"]
        if (m.get("labels") or {}).get("feeder") == "train"
    }
    assert train_feeder["feeder_stage_seconds"]["count"] == 8
    assert train_feeder["feeder_batches_total"]["value"] == 8
    assert train_feeder["feeder_depth"]["value"] >= 1
    assert "feeder_occupancy" in train_feeder
    assert "feeder_stall_seconds_total" in train_feeder

    # Span log covers the epoch and exports to valid Chrome JSON.
    events = telemetry.get_span_log().events()
    assert any(e["name"] == "train_epoch" for e in events)
    trace = json.loads(json.dumps(to_perfetto(events)))
    ts = [e["ts"] for e in trace["traceEvents"]]
    assert ts == sorted(ts) and len(ts) >= 1


def test_step_timer_observer_skips_compile_interval():
    from dss_ml_at_scale_tpu.utils import StepTimer

    seen = []
    t = StepTimer(capacity=2, observer=seen.append)
    for _ in range(5):
        t.tick()
    # 4 intervals ticked; the compile one dropped; ring holds last 2 but
    # the observer saw every recorded interval.
    assert len(seen) == 3
    assert len(t.intervals) == 2
    assert t.intervals == seen[-2:]


# ---------------------------------------------------------------------------
# serving /metrics
# ---------------------------------------------------------------------------

class _StubPredictor:
    """Predictor-shaped stub: make_server only needs meta/step/crop and
    predict() — no checkpoint or compile required for scrape tests."""

    meta = {"model": "stub"}
    step = 7
    crop = 8

    def predict(self, jpegs):
        return [{"pred_index": 0, "pred_prob": 1.0} for _ in jpegs]


@pytest.fixture()
def stub_server():
    from dss_ml_at_scale_tpu.workloads.serving import serve_in_thread

    handle = serve_in_thread(_StubPredictor())
    yield handle.port
    handle.close()


def _request(port, method, path, body=None, content_type=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    headers = {"Content-Type": content_type} if content_type else {}
    conn.request(method, path, body=body, headers=headers)
    resp = conn.getresponse()
    payload = resp.read()
    ctype = resp.getheader("Content-Type", "")
    conn.close()
    return resp.status, payload, ctype


def test_metrics_endpoint_scrape(stub_server):
    port = stub_server
    # Generate one successful predict and one 404.
    status, _, _ = _request(port, "POST", "/predict", body=b"rawbytes",
                            content_type="image/jpeg")
    assert status == 200
    status, _, _ = _request(port, "GET", "/nope")
    assert status == 404

    status, body, ctype = _request(port, "GET", "/metrics")
    assert status == 200
    assert ctype.startswith("text/plain")
    text = body.decode()
    # Prometheus exposition with the request-latency histogram.
    assert "# TYPE serving_request_seconds histogram" in text
    assert 'serving_request_seconds_bucket{path="/predict",le="+Inf"} 1' \
        in text
    assert 'serving_request_seconds_count{path="/predict"} 1' in text
    assert "# TYPE serving_errors_total counter" in text
    assert 'serving_errors_total{code="404"} 1' in text


def test_metrics_endpoint_on_fresh_server_declares_series(stub_server):
    status, body, _ = _request(stub_server, "GET", "/metrics")
    assert status == 200
    text = body.decode()
    # No traffic yet (beyond this scrape) — the families still declare
    # themselves so scrapers see stable series types.
    assert "# TYPE serving_request_seconds histogram" in text
    assert "# TYPE serving_errors_total counter" in text


def test_remote_snapshot_pull_over_rpc():
    """The multi-host discipline: a coordinator pulls a worker host's
    snapshot over the runtime/rpc control plane (the handlers every
    `dsst trial-worker` serves)."""
    from dss_ml_at_scale_tpu.parallel.trials import serve_trial_worker
    from dss_ml_at_scale_tpu.telemetry import collect_remote_snapshots

    telemetry.counter("worker_side_things").inc(5)
    server = serve_trial_worker(block=False)
    try:
        addr = f"{server.address[0]}:{server.address[1]}"
        snaps = collect_remote_snapshots([addr, "127.0.0.1:1"], timeout=5)
        names = {m["name"]: m for m in snaps[addr]["metrics"]}
        assert names["worker_side_things"]["value"] == 5.0
        # Unreachable workers degrade to an error entry, not a raise.
        assert "error" in snaps["127.0.0.1:1"]
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# run archival + CLI
# ---------------------------------------------------------------------------

def test_run_store_context_manager_and_telemetry_archive(tmp_path):
    from dss_ml_at_scale_tpu.tracking import RunStore

    telemetry.counter("archived_things").inc(3)
    with RunStore(tmp_path, "exp", run_name="ctx") as store:
        store.log_metrics({"loss": 1.0}, step=1)
        assert store.metrics()[0]["value"] == 1.0  # read-back while open
        store.log_telemetry()
    meta = json.loads((store.path / "meta.json").read_text())
    assert meta["status"] == "FINISHED"
    snap = json.loads((store.path / "telemetry.json").read_text())
    names = {m["name"]: m for m in snap["metrics"]}
    assert names["archived_things"]["value"] == 3.0
    # finish() is idempotent: the crash handler double-close is a no-op.
    store.finish("FAILED")
    assert json.loads(
        (store.path / "meta.json").read_text()
    )["status"] == "FINISHED"


def test_run_store_context_manager_marks_failed(tmp_path):
    from dss_ml_at_scale_tpu.tracking import RunStore

    with pytest.raises(RuntimeError):
        with RunStore(tmp_path, "exp") as store:
            raise RuntimeError("boom")
    meta = json.loads((store.path / "meta.json").read_text())
    assert meta["status"] == "FAILED"


def test_telemetry_cli_table_json_and_perfetto(tmp_path, capsys):
    from dss_ml_at_scale_tpu.config.cli import main

    run_dir = tmp_path / "root" / "exp" / "run1"
    (run_dir / "artifacts").mkdir(parents=True)
    (run_dir / "telemetry.json").write_text(json.dumps({
        "ts": 1.0,
        "metrics": [
            {"name": "steps", "type": "counter", "labels": {}, "value": 8},
            {"name": "lat", "type": "histogram", "labels": {"p": "/x"},
             "count": 2, "sum": 0.5,
             "buckets": [["0.1", 1], ["+Inf", 2]]},
        ],
    }))
    (run_dir / "artifacts" / "spans.jsonl").write_text(
        json.dumps({"name": "epoch", "ts": 2.0, "dur": 1.0}) + "\n"
        + json.dumps({"name": "eval", "ts": 1.0, "dur": 0.5}) + "\n"
    )

    assert main(["telemetry", "--run", str(run_dir)]) == 0
    out = capsys.readouterr().out
    assert "steps" in out and "lat{p=/x}" in out and "count=2" in out

    assert main(["telemetry", "--run", str(run_dir), "--json"]) == 0
    snap = json.loads(capsys.readouterr().out)
    assert snap["metrics"][0]["name"] == "steps"

    trace_out = tmp_path / "trace.json"
    assert main([
        "telemetry", "--run", str(run_dir),
        "--export-perfetto", str(trace_out),
    ]) == 0
    capsys.readouterr()
    trace = json.loads(trace_out.read_text())
    names = [e["name"] for e in trace["traceEvents"] if e["ph"] == "X"]
    assert names == ["eval", "epoch"]  # sorted by ts

    # Usage errors are loud, not tracebacks.
    assert main(["telemetry"]) == 2
    assert main(["telemetry", "--run", str(tmp_path / "missing")]) == 1
    capsys.readouterr()

    # A run with NO archived span log still prints its snapshot before
    # the export reports the miss.
    bare = tmp_path / "root" / "exp" / "run2"
    bare.mkdir(parents=True)
    bare.joinpath("telemetry.json").write_text(
        json.dumps({"ts": 1.0, "metrics": []})
    )
    assert main([
        "telemetry", "--run", str(bare),
        "--export-perfetto", str(tmp_path / "t2.json"),
    ]) == 1
    out = capsys.readouterr().out
    assert "(empty snapshot)" in out and "no span log" in out


# ---------------------------------------------------------------------------
# overhead budget
# ---------------------------------------------------------------------------

def test_per_step_instrumentation_under_50us():
    """The Trainer's per-step registry work (two histogram observes, a
    counter probe path, a gauge set) must stay under 50 µs on CPU."""
    from dss_ml_at_scale_tpu.analysis.sanitize import is_armed

    if is_armed():
        # A DSST_SANITIZE=1 session wraps every lock acquire with
        # bookkeeping — the budget below is the PRODUCTION (disarmed)
        # contract, and bench.py measures the armed overhead instead.
        pytest.skip("sanitizer armed: per-op budget is a disarmed contract")
    r = MetricsRegistry()
    step_hist = r.histogram("step_s")
    wait_hist = r.histogram("wait_s")
    compiles = r.counter("compiles")
    depth = r.gauge("depth")

    n = 5_000
    t0 = time.perf_counter()
    for _ in range(n):
        wait_hist.observe(1e-4)
        step_hist.observe(1e-3)
        compiles.inc(0)
        depth.set(2)
    per_step = (time.perf_counter() - t0) / n
    assert per_step < 50e-6, f"registry ops cost {per_step * 1e6:.1f} µs/step"

"""Child program for the real 2-process smoke test (test_multiprocess.py).

Each of the two OS processes runs this: connect via
``initialize_distributed`` (the reference launches its ranks with
``TorchDistributor`` + NCCL rendezvous env,
``deep_learning/2.distributed-data-loading-petastorm.py:460-470``; here
rendezvous is ``jax.distributed`` over a localhost coordinator), then
exercise every cross-process seam the framework has:

- topology: global device count spans both processes;
- data plane: a jitted global-sum over a process-spanning mesh (XLA
  inserts the cross-process all-reduce — Gloo on CPU, ICI/DCN on TPU);
- data loading: ``cur_shard=process_index / shard_count=2`` epoch with
  coverage written out so the parent can assert disjoint union;
- control plane: process 1 serves trials, process 0 drives a
  ``HostTrials`` TPE sweep against it over TCP.

Not a pytest file — launched by tests/test_multiprocess.py.
"""

import argparse
import json
import time
from pathlib import Path


def _wait_for(path: Path, timeout: float = 120.0) -> None:
    deadline = time.monotonic() + timeout
    while not path.exists():
        if time.monotonic() > deadline:
            raise TimeoutError(f"timed out waiting for {path}")
        time.sleep(0.05)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--coordinator", required=True)
    ap.add_argument("--process-id", type=int, required=True)
    ap.add_argument("--num-processes", type=int, default=2)
    ap.add_argument("--data", required=True)
    ap.add_argument("--workdir", required=True)
    ap.add_argument(
        "--train-data", default=None,
        help="JPEG Delta table; when set, both processes also run a "
        "multi-host `dsst train` epoch over it",
    )
    args = ap.parse_args()
    workdir = Path(args.workdir)

    import jax

    # Env JAX_PLATFORMS is overridden by preregistered PJRT plugins on
    # some hosts; force the CPU platform in-process (tests/conftest.py
    # does the same).
    jax.config.update("jax_platforms", "cpu")

    from dss_ml_at_scale_tpu.runtime import (
        initialize_distributed,
        local_topology,
        make_mesh,
    )

    initialize_distributed(
        coordinator_address=args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
    )
    topo = local_topology()
    result = {
        "process_index": topo.process_index,
        "process_count": topo.process_count,
        "global_devices": topo.global_device_count,
        "local_devices": topo.local_device_count,
    }

    # -- data plane: global reduction across both processes' devices ------
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dss_ml_at_scale_tpu.runtime.mesh import shard_batch_to_mesh

    mesh = make_mesh()
    contrib = np.full(
        topo.local_device_count, float(topo.process_index + 1), np.float32
    )
    x = shard_batch_to_mesh({"v": contrib}, mesh)["v"]
    total = jax.jit(lambda a: a.sum(), out_shardings=NamedSharding(mesh, P()))(x)
    result["global_sum"] = float(total)

    # -- data loading: disjoint shard coverage (2...py:249-250) ------------
    from dss_ml_at_scale_tpu.data import DeltaTable
    from dss_ml_at_scale_tpu.data.reader import ParquetShardReader

    table = DeltaTable(args.data)
    ids: list[int] = []
    with ParquetShardReader(
        table.file_uris(),
        batch_size=4,
        cur_shard=topo.process_index,
        shard_count=topo.process_count,
        num_epochs=1,
        shuffle_row_groups=False,
        drop_last=False,
        columns=["id"],
    ) as reader:
        for batch in reader:
            ids.extend(int(v) for v in batch["id"])
    result["ids"] = sorted(ids)

    # -- control plane: HostTrials sweep driven by process 0 against a
    # worker served by EVERY other process (N-1 workers at N>2) --------
    done_file = workdir / "sweep_done"
    if topo.process_index > 0:
        from dss_ml_at_scale_tpu.parallel.trials import serve_trial_worker

        server = serve_trial_worker("127.0.0.1:0", block=False)
        host, port = server.address
        (workdir / f"worker_addr_{topo.process_index}").write_text(
            f"{host}:{port}"
        )
        _wait_for(done_file)
    else:
        addrs = []
        for i in range(1, topo.process_count):
            f = workdir / f"worker_addr_{i}"
            _wait_for(f)
            addrs.append(f.read_text())
        from dss_ml_at_scale_tpu.hpo import fmin, hp
        from dss_ml_at_scale_tpu.parallel import HostTrials

        trials = HostTrials(addrs, parallelism=len(addrs))
        best = fmin(
            "dss_ml_at_scale_tpu.hpo.objectives:quadratic",
            {"x": hp.uniform("x", -5.0, 5.0)},
            max_evals=2 * len(addrs) + 2,
            trials=trials,
            rstate=np.random.default_rng(0),
        )
        result["hpo_best_x"] = float(best["x"])
        result["hpo_ok_trials"] = sum(
            1 for t in trials.trials if t["result"]["status"] == "ok"
        )
        done_file.write_text("done")

    # -- real multi-host DP training through the train CLI ----------------
    # Both processes run the same `dsst train` command; the trainer
    # builds a global 2-device mesh, each process decodes its own reader
    # shard, and `shard_batch_to_mesh` assembles per-process rows into
    # the global batch (the reference's 4x4 TorchDistributor shape,
    # 2...py:460-470, at N=2 on localhost).
    if args.train_data:
        import contextlib
        import io

        from dss_ml_at_scale_tpu.config.cli import main as cli_main

        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = cli_main([
                "train", "--data", args.train_data, "--model", "tiny",
                "--num-classes", "4", "--crop", "64", "--batch-size", "8",
                "--epochs", "1", "--learning-rate", "0.01",
            ])
        result["train_rc"] = rc
        if rc == 0:
            summary = json.loads(buf.getvalue().strip().splitlines()[-1])
            result["train_steps"] = summary["steps"]
            result["train_loss"] = summary["train_loss"]
        else:
            # Surface the CLI's own output instead of dying on a parse of
            # an empty buffer (which would also drop the earlier results).
            result["train_output"] = buf.getvalue()[-2000:]

    # -- write result; filesystem barrier so neither process exits while
    #    the other still needs the jax.distributed service ----------------
    (workdir / f"result_{topo.process_index}.json").write_text(
        json.dumps(result)
    )
    for i in range(topo.process_count):
        _wait_for(workdir / f"result_{i}.json")


if __name__ == "__main__":
    main()

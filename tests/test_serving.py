"""HTTP inference serving (workloads/serving.py, `dsst serve`).

The platform-deployment face (reference users get this from Databricks
model serving): a trained checkpoint behind GET /healthz + POST
/predict, one fixed-shape compiled scorer, vocabulary label names.
"""

import base64
import http.client
import json

import numpy as np
import pytest


@pytest.fixture(scope="module")
def trained_ckpt(tmp_path_factory, devices8):
    """A tiny trained checkpoint over real JPEGs, with a label
    vocabulary — shared by every serving test."""
    import pyarrow as pa

    from test_end_to_end import _jpeg

    from dss_ml_at_scale_tpu.config.cli import main
    from dss_ml_at_scale_tpu.data import write_delta

    root = tmp_path_factory.mktemp("serve")
    rng = np.random.default_rng(0)
    labels = rng.integers(0, 4, 48)
    jpegs = [_jpeg(rng, l) for l in labels]
    table = pa.table({
        "content": pa.array(jpegs, type=pa.binary()),
        "label_index": pa.array(labels.astype(np.int64)),
    })
    data = root / "images"
    write_delta(table, data, max_rows_per_file=16)
    # A vocabulary like dsst ingest writes; train persists it with the
    # checkpoint, and serve must name classes from it.
    (data / "labels.json").write_text(
        json.dumps({"cat": 0, "dog": 1, "fox": 2, "owl": 3})
    )

    ckpt = root / "ckpt"
    assert main([
        "train", "--data", str(data), "--model", "tiny",
        "--num-classes", "4", "--crop", "64", "--batch-size", "16",
        "--epochs", "1", "--checkpoint-dir", str(ckpt),
    ]) == 0
    return ckpt, jpegs


@pytest.fixture(scope="module")
def server(trained_ckpt):
    from dss_ml_at_scale_tpu.workloads.serving import (
        Predictor,
        serve_in_thread,
    )

    ckpt, jpegs = trained_ckpt
    predictor = Predictor(str(ckpt), micro_batch=4)
    handle = serve_in_thread(predictor)
    yield handle.port, jpegs
    handle.close()


def _request(port, method, path, body=None, content_type=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    headers = {"Content-Type": content_type} if content_type else {}
    conn.request(method, path, body=body, headers=headers)
    resp = conn.getresponse()
    payload = json.loads(resp.read())
    conn.close()
    return resp.status, payload


def test_healthz(server):
    port, _ = server
    status, payload = _request(port, "GET", "/healthz")
    assert status == 200
    assert payload["status"] == "ok"
    assert payload["state"] == "ready"
    assert payload["model"] == "tiny"
    assert payload["crop"] == 64


def test_readyz_while_serving(server):
    port, _ = server
    status, payload = _request(port, "GET", "/readyz")
    assert status == 200
    assert payload["ready"] is True


def test_predict_raw_jpeg(server):
    port, jpegs = server
    status, payload = _request(
        port, "POST", "/predict", body=jpegs[0],
        content_type="image/jpeg",
    )
    assert status == 200
    (pred,) = payload["predictions"]
    assert 0 <= pred["pred_index"] < 4
    assert 0.0 < pred["pred_prob"] <= 1.0
    assert pred["pred_label"] in {"cat", "dog", "fox", "owl"}


def test_predict_json_batch_pads_and_chunks(server):
    port, jpegs = server
    # 7 instances through a micro_batch-4 scorer: one full chunk + one
    # padded chunk, order preserved.
    body = json.dumps(
        {"instances": [base64.b64encode(j).decode() for j in jpegs[:7]]}
    )
    status, payload = _request(
        port, "POST", "/predict", body=body,
        content_type="application/json",
    )
    assert status == 200
    assert len(payload["predictions"]) == 7
    # Same images one at a time agree with the batched pass (padding
    # must not leak into real rows).
    for i in (0, 4, 6):
        status, single = _request(
            port, "POST", "/predict", body=jpegs[i],
            content_type="image/jpeg",
        )
        assert single["predictions"][0] == payload["predictions"][i]


def test_malformed_input_is_400_not_fatal(server):
    port, jpegs = server
    status, payload = _request(
        port, "POST", "/predict", body=b"{not json",
        content_type="application/json",
    )
    assert status == 400 and "error" in payload
    status, payload = _request(
        port, "POST", "/predict",
        body=json.dumps({"instances": []}),
        content_type="application/json",
    )
    assert status == 400
    # The server survives bad requests and keeps serving.
    status, _ = _request(port, "GET", "/healthz")
    assert status == 200


def test_unknown_route_404(server):
    port, _ = server
    assert _request(port, "GET", "/nope")[0] == 404
    assert _request(port, "POST", "/nope")[0] == 404


def test_metrics_scrape_includes_predictor_series(server):
    """GET /metrics on a REAL Predictor-backed server: the scoring-path
    histograms registered in Predictor.__init__ render alongside the
    HTTP-layer series (the stub-server scrape lives in
    test_telemetry.py)."""
    import http.client

    port, jpegs = server
    status, _ = _request(
        port, "POST", "/predict", body=jpegs[0],
        content_type="image/jpeg",
    )
    assert status == 200
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("GET", "/metrics")
    resp = conn.getresponse()
    text = resp.read().decode()
    ctype = resp.getheader("Content-Type", "")
    conn.close()
    assert resp.status == 200
    assert ctype.startswith("text/plain")
    assert "# TYPE serving_request_seconds histogram" in text
    assert 'serving_request_seconds_bucket{path="/predict",le="+Inf"}' in text
    assert "# TYPE predict_batch_seconds histogram" in text
    assert "predict_batch_seconds_count" in text
    assert "predict_images_total" in text
    # The scheduler's series render on the same scrape: this request
    # rode a scored batch, and the gauge/queue families declare.
    assert "# TYPE serving_batch_fill histogram" in text
    assert "serving_batch_fill_count" in text
    assert "# TYPE serving_queue_depth gauge" in text
    assert "# TYPE serving_time_in_queue_seconds histogram" in text


def test_serving_matches_dsst_predict(server, trained_ckpt, tmp_path):
    """The guarantee the module docstring makes: the server scores the
    SAME pixels as dsst predict (shared transform spec — resize-256
    field of view, normalization, decode backend), so pred_index agrees
    row for row."""
    from dss_ml_at_scale_tpu.config.cli import main
    from dss_ml_at_scale_tpu.config.commands import _read_delta_pandas

    port, jpegs = server
    ckpt, _ = trained_ckpt
    data = ckpt.parent / "images"
    out = tmp_path / "preds"
    assert main([
        "predict", "--data", str(data), "--checkpoint-dir", str(ckpt),
        "--out", str(out), "--batch-size", "16",
    ]) == 0
    table_preds = _read_delta_pandas(out).sort_values("row")

    for i in (0, 7, 23):
        status, payload = _request(
            port, "POST", "/predict", body=jpegs[i],
            content_type="image/jpeg",
        )
        assert status == 200
        served = payload["predictions"][0]
        assert served["pred_index"] == int(table_preds["pred_index"].iloc[i])


@pytest.mark.slow
def test_serving_vit_checkpoint(tmp_path, devices8):
    """The server resolves and serves a ViT checkpoint too (stat-free
    restore through the shared resolver)."""
    import pyarrow as pa

    from test_end_to_end import _jpeg

    from dss_ml_at_scale_tpu.config.cli import main
    from dss_ml_at_scale_tpu.data import write_delta
    from dss_ml_at_scale_tpu.workloads.serving import (
        Predictor,
        serve_in_thread,
    )

    rng = np.random.default_rng(0)
    labels = rng.integers(0, 4, 32)
    jpegs = [_jpeg(rng, l) for l in labels]
    table = pa.table({
        "content": pa.array(jpegs, type=pa.binary()),
        "label_index": pa.array(labels.astype(np.int64)),
    })
    data = tmp_path / "images"
    write_delta(table, data, max_rows_per_file=16)
    ckpt = tmp_path / "ckpt"
    assert main([
        "train", "--data", str(data), "--model", "vit-tiny",
        "--num-classes", "4", "--crop", "64", "--batch-size", "16",
        "--epochs", "1", "--checkpoint-dir", str(ckpt),
    ]) == 0

    predictor = Predictor(str(ckpt), micro_batch=4)
    handle = serve_in_thread(predictor)
    try:
        port = handle.port
        status, payload = _request(
            port, "POST", "/predict", body=jpegs[0],
            content_type="image/jpeg",
        )
        assert status == 200
        assert 0 <= payload["predictions"][0]["pred_index"] < 4
        status, health = _request(port, "GET", "/healthz")
        assert health["model"] == "vit-tiny"
    finally:
        handle.close()

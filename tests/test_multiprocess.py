"""Real 2-process distributed tests.

Everything else in the suite simulates multi-device on one process
(conftest's 8 virtual CPU devices). These tests launch TWO actual OS
processes connected through ``jax.distributed`` on a localhost
coordinator — the shape the reference runs as 4 nodes × 4 GPUs via
``TorchDistributor`` (``deep_learning/2...py:460-470``) — and assert:

- both processes see the global topology (2 processes, 2 devices);
- a jitted reduction over a process-spanning mesh produces the global
  answer on both (the cross-process collective actually ran);
- ``cur_shard/shard_count`` reader shards cover the table disjointly
  across *processes* (not just simulated devices);
- a ``HostTrials`` sweep driven from process 0 evaluates trials on a
  worker served by process 1 (control plane crosses the boundary);
- (slow) a full multi-host ``dsst train`` epoch: per-process reader
  shards assembled into the global batch on a process-spanning mesh.
"""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pyarrow as pa
import pytest

CHILD = Path(__file__).parent / "mp_child.py"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch_pair(tmp_path, data, extra_args=(), n=2):
    # The parent pytest process forces 8 simulated devices via XLA_FLAGS;
    # children must not inherit that (1 CPU device per process).
    env = dict(os.environ)
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count")
    )
    # Children import the package from the repo root; APPEND to
    # PYTHONPATH (overwriting would drop the host's PJRT plugin path).
    repo_root = str(Path(__file__).parent.parent)
    env["PYTHONPATH"] = os.pathsep.join(
        [repo_root] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else [])
    )
    coordinator = f"127.0.0.1:{_free_port()}"
    procs = [
        subprocess.Popen(
            [
                sys.executable, str(CHILD),
                "--coordinator", coordinator,
                "--process-id", str(pid),
                "--num-processes", str(n),
                "--data", str(data),
                "--workdir", str(tmp_path),
                *extra_args,
            ],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(n)
    ]
    outputs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300 * max(1, n // 2))
            outputs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outputs):
        assert p.returncode == 0, f"child failed:\n{out[-3000:]}"
    return [
        json.loads((tmp_path / f"result_{i}.json").read_text())
        for i in range(n)
    ]


def _id_table(tmp_path):
    from dss_ml_at_scale_tpu.data import write_delta

    table = pa.table({"id": pa.array(np.arange(16, dtype=np.int64))})
    data = tmp_path / "table"
    write_delta(table, data, max_rows_per_file=4)
    return data


def test_two_process_distributed_smoke(tmp_path):
    results = _launch_pair(tmp_path, _id_table(tmp_path))
    for r in results:
        assert r["process_count"] == 2
        assert r["global_devices"] == 2
        assert r["local_devices"] == 1
        # sum over devices: proc0 contributes 1.0, proc1 contributes 2.0
        assert r["global_sum"] == 3.0
    # Disjoint shard coverage across processes, union = whole table.
    ids0, ids1 = set(results[0]["ids"]), set(results[1]["ids"])
    assert ids0.isdisjoint(ids1)
    assert ids0 | ids1 == set(range(16))
    # The HPO sweep ran on the other process's worker.
    assert results[0]["hpo_ok_trials"] == 4
    assert -5.0 <= results[0]["hpo_best_x"] <= 5.0


@pytest.mark.slow
def test_four_process_distributed(tmp_path):
    """N>2 coordination on localhost — the reference's flagship shape is
    4 nodes x 4 GPUs (``deep_learning/2...py:460-470``); this exercises
    the N=4 process topology end to end: 4-device global mesh with a
    cross-process collective, 4-way disjoint reader shards, and a
    HostTrials sweep scheduling onto THREE worker processes."""
    results = _launch_pair(tmp_path, _id_table(tmp_path), n=4)
    for r in results:
        assert r["process_count"] == 4
        assert r["global_devices"] == 4
        assert r["local_devices"] == 1
        # sum over devices: process i contributes i+1 -> 1+2+3+4
        assert r["global_sum"] == 10.0
    shards = [set(r["ids"]) for r in results]
    for i in range(4):
        for j in range(i + 1, 4):
            assert shards[i].isdisjoint(shards[j])
    assert set().union(*shards) == set(range(16))
    # Sweep spread across the 3 workers; every trial succeeded.
    assert results[0]["hpo_ok_trials"] == 8
    assert -5.0 <= results[0]["hpo_best_x"] <= 5.0


@pytest.mark.slow
def test_two_process_training(tmp_path):
    sys.path.insert(0, str(Path(__file__).parent))
    from test_end_to_end import _jpeg

    from dss_ml_at_scale_tpu.data import write_delta

    rng = np.random.default_rng(0)
    labels = rng.integers(0, 4, 64)
    images = pa.table({
        "content": pa.array([_jpeg(rng, l) for l in labels], type=pa.binary()),
        "label_index": pa.array(labels.astype(np.int64)),
    })
    train_data = tmp_path / "images"
    write_delta(images, train_data, max_rows_per_file=16)

    results = _launch_pair(
        tmp_path, _id_table(tmp_path),
        extra_args=["--train-data", str(train_data)],
    )
    # Multi-host DP training: steps/epoch = rows // (batch x world)
    # = 64 // (8 x 2) = 4, identical on both ranks, finite loss.
    for r in results:
        assert r["train_rc"] == 0
        assert r["train_steps"] == 4
        assert np.isfinite(r["train_loss"])

"""Feeder-pipeline suite (PR 5).

The properties that matter:

- **Overlap**: host-side shard + enqueue runs on the feeder thread, so a
  slow-but-keeping-up source never blocks the step loop.
- **Backpressure**: the bounded queue caps how far the feeder runs ahead
  (at most ``depth`` staged batches of HBM).
- **Lifecycle**: source exhaustion ends iteration cleanly, a source
  exception re-raises in the consumer, and ``close()`` (every Trainer
  exit path) unblocks and joins the thread — no feeder outlives its loop.
- **PR 4 parity**: provenance rides the queue WITH its batch, so a
  poisoned run through the feeder still ends bitwise-identical to the
  clean run, and the quarantined rows are exactly the poison batch's.
"""

import threading
import time

import numpy as np
import optax
import pytest

import jax

from dss_ml_at_scale_tpu import telemetry
from dss_ml_at_scale_tpu.data.prefetch import (
    DeviceFeeder,
    Feeder,
    MeshFeeder,
    prefetch_to_devices,
)
from dss_ml_at_scale_tpu.parallel import ClassifierTask, Trainer, TrainerConfig
from dss_ml_at_scale_tpu.resilience import (
    FaultPlan,
    QuarantineList,
    RowRange,
    faults,
)
from dss_ml_at_scale_tpu.resilience.health import HealthConfig
from dss_ml_at_scale_tpu.resilience.rollback import PROVENANCE_KEY
from dss_ml_at_scale_tpu.runtime import make_mesh
from dss_ml_at_scale_tpu.runtime.mesh import get_batch_placer

from test_models import tiny_resnet
from test_trainer import synthetic_batches


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.clear()


def _feeder_threads():
    return [
        t for t in threading.enumerate()
        if t.name.startswith("feeder-") and t.is_alive()
    ]


def _assert_no_feeder_threads():
    # close() joins with a timeout; give a straggler one grace window.
    deadline = time.monotonic() + 2.0
    while _feeder_threads() and time.monotonic() < deadline:
        time.sleep(0.02)
    assert _feeder_threads() == []


# -- mechanics ---------------------------------------------------------------

def test_mesh_feeder_yields_in_order_and_shards(devices8):
    mesh = make_mesh()
    batches = [{"x": np.full((8, 2), i, np.float32)} for i in range(6)]
    with MeshFeeder(iter(batches), mesh, depth=3, name="t-order") as feeder:
        out = list(feeder)
    assert len(out) == 6
    for i, (b, prov) in enumerate(out):
        assert prov is None
        assert float(np.asarray(b["x"]).mean()) == i
        assert len(b["x"].sharding.device_set) == 8
    _assert_no_feeder_threads()


def test_feeder_strips_provenance_and_pairs_it(devices8):
    mesh = make_mesh()
    batches = []
    for i in range(4):
        batches.append({
            "x": np.full((8, 2), i, np.float32),
            PROVENANCE_KEY: [RowRange("mem://t", i, 0, 8)],
        })
    with MeshFeeder(iter(batches), mesh, depth=2, name="t-prov") as feeder:
        for i, (b, prov) in enumerate(feeder):
            # The side channel never reaches device_put, and each batch
            # arrives WITH its own provenance — parity by construction.
            assert PROVENANCE_KEY not in b
            assert prov[0].row_group == i
            assert float(np.asarray(b["x"]).mean()) == i


def test_overlap_slow_but_keeping_up_source_never_blocks_step_loop():
    """Source takes 10 ms/batch, 'step' takes 30 ms: pull-driven, every
    batch's 10 ms would land on the consumer thread (~100 ms over 10
    steps); through the feeder the consumer's wait collapses to ~the
    first fill."""
    producer_delay, step_delay, n = 0.01, 0.03, 10

    def source():
        for i in range(n + 2):
            time.sleep(producer_delay)
            yield {"i": i}

    feeder = Feeder(source(), place=lambda b: b, depth=2, name="t-overlap")
    try:
        next(feeder)  # warmup: first fill
        waited = 0.0
        for _ in range(n):
            t0 = time.perf_counter()
            next(feeder)
            waited += time.perf_counter() - t0
            time.sleep(step_delay)  # the "train step"
        # Serialized cost would be ~n * producer_delay; overlapped must
        # be well under half of it (generous margin for CI jitter).
        assert waited < 0.5 * n * producer_delay, waited
    finally:
        feeder.close()


def test_backpressure_bounds_run_ahead():
    pulled = []

    def source():
        for i in range(100):
            pulled.append(i)
            yield {"i": i}

    feeder = Feeder(source(), place=lambda b: b, depth=2, name="t-bp")
    try:
        time.sleep(0.3)  # consumer takes nothing
        # depth staged in the queue + one finished batch blocked on put
        # + one being staged: the feeder never runs further ahead.
        assert len(pulled) <= 2 + 2
        assert feeder.occupancy == 2
        got = [b["i"] for b, _ in (next(feeder) for _ in range(4))]
        assert got == [0, 1, 2, 3]  # order preserved under backpressure
    finally:
        feeder.close()
    _assert_no_feeder_threads()


def test_source_exception_reraises_in_consumer_and_thread_dies():
    class Boom(RuntimeError):
        pass

    def source():
        yield {"i": 0}
        yield {"i": 1}
        raise Boom("decode failed")

    feeder = Feeder(source(), place=lambda b: b, depth=4, name="t-err")
    try:
        assert next(feeder)[0]["i"] == 0
        assert next(feeder)[0]["i"] == 1
        with pytest.raises(Boom, match="decode failed"):
            next(feeder)
        # Exhausted by failure: subsequent reads stay terminal.
        with pytest.raises(StopIteration):
            next(feeder)
    finally:
        feeder.close()
    _assert_no_feeder_threads()


def test_close_unblocks_producer_stuck_on_full_queue():
    def source():
        i = 0
        while True:
            yield {"i": i}
            i += 1

    feeder = Feeder(source(), place=lambda b: b, depth=1, name="t-close")
    time.sleep(0.1)  # producer fills the queue and blocks on put
    feeder.close()
    _assert_no_feeder_threads()
    # Closed under the consumer: a clean StopIteration, not a hang.
    with pytest.raises(StopIteration):
        next(feeder)


def test_depth_validation_and_compat_wrappers(devices8):
    with pytest.raises(ValueError):
        Feeder(iter([]), place=lambda b: b, depth=0)
    with pytest.raises(ValueError):
        list(prefetch_to_devices(iter([]), depth=0))
    # The compat wrapper yields plain batches (no provenance pairs).
    out = list(prefetch_to_devices(
        iter([{"x": np.ones((4,), np.float32)}]), depth=2
    ))
    assert len(out) == 1 and np.asarray(out[0]["x"]).sum() == 4.0
    _assert_no_feeder_threads()


def test_device_feeder_occupancy_gauge_and_counters(devices8):
    batches = [{"x": np.ones((4,), np.float32)} for _ in range(5)]
    with DeviceFeeder(iter(batches), depth=2, name="t-metrics") as feeder:
        list(feeder)
    snap = {
        m["name"]: m
        for m in telemetry.snapshot()["metrics"]
        if (m.get("labels") or {}).get("feeder") == "t-metrics"
    }
    assert snap["feeder_batches_total"]["value"] == 5
    assert snap["feeder_depth"]["value"] == 2
    assert snap["feeder_stage_seconds"]["count"] == 5
    assert "feeder_occupancy" in snap
    assert "feeder_stall_seconds_total" in snap


# -- placer caching ----------------------------------------------------------

def test_batch_placer_caches_shardings_and_plans(devices8):
    mesh = make_mesh()
    placer = get_batch_placer(mesh)
    assert get_batch_placer(mesh) is placer  # shared per (mesh, axis, specs)
    b1 = placer({"x": np.ones((8, 2), np.float32), "n": np.float32(3.0)})
    n_plans = len(placer._plans)
    b2 = placer({"x": np.zeros((8, 2), np.float32), "n": np.float32(4.0)})
    # Same structure -> one cached plan, shardings reused.
    assert len(placer._plans) == n_plans
    assert b1["x"].sharding is b2["x"].sharding
    assert float(np.asarray(b2["n"])) == 4.0
    # Validation still exact on a fresh (bad) structure: nothing cached.
    with pytest.raises(ValueError, match="not divisible"):
        placer({"x": np.ones((7, 2), np.float32)})


# -- trainer integration: lifecycle + PR 4 parity ----------------------------

def _task():
    return ClassifierTask(model=tiny_resnet(num_classes=4),
                          tx=optax.adam(1e-2))


def _fit(batches, health=None, **cfg):
    trainer = Trainer(
        TrainerConfig(log_every_steps=1000, health=health, **cfg),
        mesh=make_mesh(),
    )
    return trainer.fit(_task(), iter([dict(b) for b in batches]))


def test_fit_closes_feeder_on_exhaustion_and_completion(devices8):
    result = _fit(synthetic_batches(6), max_epochs=2, steps_per_epoch=4)
    # Data ran out mid-epoch-2: the loop stopped AND the feeder died.
    assert int(result.state.step) == 6
    _assert_no_feeder_threads()


def test_fit_closes_feeder_on_health_abort(devices8):
    from dss_ml_at_scale_tpu.resilience.health import TrainingHealthError

    faults.install(FaultPlan.parse("grads.nonfinite=1@1"))
    with pytest.raises(TrainingHealthError):
        _fit(
            synthetic_batches(6), HealthConfig(policy="abort"),
            max_epochs=1, steps_per_epoch=4,
        )
    _assert_no_feeder_threads()


def test_poisoned_run_through_feeder_matches_clean_run_bitwise(
    devices8, tmp_path
):
    """The PR 4 acceptance property, through the new feeder path: a
    grads.nonfinite step discarded under policy=skip leaves final params
    bitwise-identical to a clean run without the poison batch, and the
    quarantined rows are exactly the poison batch's provenance — proof
    the (batch, provenance) pairing survives the queue."""
    q = QuarantineList(tmp_path / "quarantine.jsonl")
    batches = [dict(b) for b in synthetic_batches(10)]
    for i, b in enumerate(batches):
        b[PROVENANCE_KEY] = [RowRange("mem://train", i, 0, 16)]

    faults.install(FaultPlan.parse("grads.nonfinite=1@3"))
    poisoned = _fit(
        batches, HealthConfig(policy="skip", quarantine=q),
        max_epochs=2, steps_per_epoch=4,
    )
    faults.clear()
    clean = _fit(
        [b for i, b in enumerate(batches) if i != 3],
        HealthConfig(policy="skip"),
        max_epochs=2, steps_per_epoch=4,
    )

    assert int(poisoned.state.step) == 8 == int(clean.state.step)
    assert poisoned.skipped_steps == 1
    # Row-exact: batch 3 (the 4th pulled) is the quarantined one.
    assert len(q) == 1 and q.entries[0]["row_group"] == 3
    for x, y in zip(
        jax.tree_util.tree_leaves(poisoned.state.params),
        jax.tree_util.tree_leaves(clean.state.params),
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    _assert_no_feeder_threads()

"""Bench harness children as subprocesses (the driver-facing surface).

The driver runs ``python bench.py`` and consumes one JSON line; the
parent/watchdog logic is exercised against a possibly-hung tunnel in
production, so what CI can and should pin is the CHILD contract: each
child prints exactly one parseable JSON object on stdout and honors the
forced-CPU env. The heavyweight train child is covered by the slow CLI
and end-to-end suites; probe and lm are cheap enough to run here.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_child(mode: str, timeout: float, partial_path: str | None = None):
    env = dict(
        os.environ,
        DSST_BENCH_CHILD="1",
        DSST_BENCH_MODE=mode,
        DSST_BENCH_FORCE_CPU="1",
    )
    if partial_path:
        env["DSST_BENCH_PARTIAL"] = partial_path
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env, capture_output=True, text=True, timeout=timeout,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = proc.stdout.strip().splitlines()
    assert lines, "child printed nothing"
    return json.loads(lines[-1])


def test_probe_child_reports_platform():
    out = _run_child("probe", timeout=120)
    assert out.get("platform") == "cpu"
    assert out.get("n", 0) >= 1
    assert not out.get("failed")


@pytest.mark.slow
def test_lm_child_measures_tokens_per_sec():
    out = _run_child("lm", timeout=420)
    assert not out.get("failed"), out.get("note")
    assert out["platform"] == "cpu"
    assert out["tokens_per_sec"] > 0
    # CPU fallback shape: reference attention, shrunk geometry.
    assert out["attention"] == "reference"
    assert out["seq_len"] == 256


@pytest.mark.slow
def test_train_child_checkpoints_partial_and_resumes(tmp_path):
    """A watchdog-killed attempt must not lose completed sweep points.

    The train child checkpoints ``result`` to DSST_BENCH_PARTIAL after
    every sweep point / section; a second attempt with the same file
    skips completed batches (the round-4 live tunnel needed this: two
    900 s attempts each restarting from zero never finished)."""
    partial = tmp_path / "train.json"
    out1 = _run_child("train", timeout=600, partial_path=str(partial))
    assert not out1.get("failed"), out1.get("note")
    assert out1["value"] > 0
    # The checkpoint file holds the same completed measurement.
    saved = json.loads(partial.read_text())
    assert saved["platform"] == "cpu"
    assert saved["value"] > 0
    assert any("images_per_sec" in p for p in saved["sweep"])
    assert "pipeline" in saved
    # Empty-success profile still marks the section done (cpu traces
    # carry no TPU events, so the category list is empty).
    assert saved["profile"] == {"top_hlo_categories": []}

    # Poison the saved throughput: a resumed run must REUSE the sweep
    # point (proving it skipped re-measurement) and not recompute it.
    saved["sweep"] = [
        dict(p, images_per_sec=12345.0) if "images_per_sec" in p else p
        for p in saved["sweep"]
    ]
    saved["value"] = 12345.0
    partial.write_text(json.dumps(saved))
    out2 = _run_child("train", timeout=600, partial_path=str(partial))
    assert not out2.get("failed"), out2.get("note")
    assert out2["value"] == 12345.0
    if "error" not in out1["pipeline"]:
        # An errored section is deliberately NOT treated as done (the
        # resume re-runs it), so byte-equality only holds for a clean one.
        assert out2["pipeline"] == out1["pipeline"]


def test_parent_salvages_partial_over_cpu_fallback(tmp_path):
    """bench._salvage contract: an on-accel partial with a real headline
    is salvaged; a cpu partial, a headline-less partial (e.g. only the
    tunnel probe ran), and a missing file are not."""
    import bench

    path = tmp_path / "p.json"
    assert bench._salvage(str(path), "value") is None  # missing file
    path.write_text(json.dumps({"platform": "cpu", "value": 5.0}))
    assert bench._salvage(str(path), "value") is None  # cpu partial
    path.write_text(json.dumps({"platform": "tpu", "tunnel": {}}))
    assert bench._salvage(str(path), "value") is None  # no headline yet
    path.write_text(
        json.dumps({"platform": "tpu", "value": 2000.0, "sweep": []})
    )
    salvaged = bench._salvage(str(path), "value")
    assert salvaged and salvaged["value"] == 2000.0
    # Child-side helpers round-trip through the env handle.
    os.environ["DSST_BENCH_PARTIAL"] = str(path)
    try:
        loaded = bench._load_partial()
        assert loaded == salvaged
        bench._save_partial({"platform": "tpu", "value": 1.0})
        assert json.loads(path.read_text())["value"] == 1.0
    finally:
        os.environ.pop("DSST_BENCH_PARTIAL", None)


@pytest.mark.slow
def test_vit_child_measures_images_per_sec():
    out = _run_child("vit", timeout=420)
    assert not out.get("failed"), out.get("note")
    assert out["platform"] == "cpu"
    assert out["model"] == "vit_micro"
    assert out["images_per_sec"] > 0

"""Bench harness children as subprocesses (the driver-facing surface).

The driver runs ``python bench.py`` and consumes one JSON line; the
parent/watchdog logic is exercised against a possibly-hung tunnel in
production, so what CI can and should pin is the CHILD contract: each
child prints exactly one parseable JSON object on stdout and honors the
forced-CPU env. The heavyweight train child is covered by the slow CLI
and end-to-end suites; probe and lm are cheap enough to run here.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_child(mode: str, timeout: float):
    env = dict(
        os.environ,
        DSST_BENCH_CHILD="1",
        DSST_BENCH_MODE=mode,
        DSST_BENCH_FORCE_CPU="1",
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env, capture_output=True, text=True, timeout=timeout,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = proc.stdout.strip().splitlines()
    assert lines, "child printed nothing"
    return json.loads(lines[-1])


def test_probe_child_reports_platform():
    out = _run_child("probe", timeout=120)
    assert out.get("platform") == "cpu"
    assert out.get("n", 0) >= 1
    assert not out.get("failed")


@pytest.mark.slow
def test_lm_child_measures_tokens_per_sec():
    out = _run_child("lm", timeout=420)
    assert not out.get("failed"), out.get("note")
    assert out["platform"] == "cpu"
    assert out["tokens_per_sec"] > 0
    # CPU fallback shape: reference attention, shrunk geometry.
    assert out["attention"] == "reference"
    assert out["seq_len"] == 256
